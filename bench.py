"""Headline benchmark: eval samples/sec/chip on the PPL + generation paths.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors the reference's hot loops (SURVEY.md §3.2-3.3): batched
PPL scoring (one forward + shifted CE per batch — the MMLU/PIQA-style
ranking path) and batched greedy generation (the GSM8K-style path), on a
llama-family model in bf16.  The reference publishes no perf numbers
(BASELINE.md), so ``vs_baseline`` compares against the previous round's
recorded value when available (BENCH_r*.json), else 1.0.

Run on whatever jax.devices() offers (the driver provides one real TPU
chip); value is normalized per chip.
"""
import glob
import json
import os
import re
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from opencompass_tpu.nn import (TransformerConfig, forward, greedy_generate,
                                init_params, sequence_nll)

# llama-shaped; sized so bench (compile + run) stays under ~3 min on one chip
CFG = TransformerConfig.llama(
    vocab_size=32000, hidden_size=1024, num_layers=8, num_heads=16,
    num_kv_heads=16, intermediate_size=2816, max_seq_len=2048)

PPL_BATCH, PPL_SEQ, PPL_ITERS = 32, 512, 8
GEN_BATCH, GEN_PROMPT, GEN_NEW = 16, 128, 64


def _bench_ppl(params):
    @jax.jit
    def step(params, tokens, mask):
        return sequence_nll(forward(params, CFG, tokens, mask), tokens, mask)

    tokens = jnp.ones((PPL_BATCH, PPL_SEQ), jnp.int32)
    mask = jnp.ones((PPL_BATCH, PPL_SEQ), jnp.bool_)
    # host fetch (not block_until_ready) to fully drain compile + queue:
    # some PJRT backends return from block early while work is in flight
    np.asarray(step(params, tokens, mask))
    t0 = time.perf_counter()
    for _ in range(PPL_ITERS):
        out = step(params, tokens, mask)
    np.asarray(out)
    dt = time.perf_counter() - t0
    return PPL_BATCH * PPL_ITERS / dt


def _bench_gen(params):
    @jax.jit
    def step(params, tokens, mask):
        return greedy_generate(params, CFG, tokens, mask, GEN_NEW,
                               eos_token_id=None)[0]

    tokens = jnp.ones((GEN_BATCH, GEN_PROMPT), jnp.int32)
    mask = jnp.ones((GEN_BATCH, GEN_PROMPT), jnp.bool_)
    np.asarray(step(params, tokens, mask))  # compile + full sync
    t0 = time.perf_counter()
    out = step(params, tokens, mask)
    np.asarray(out)
    dt = time.perf_counter() - t0
    return GEN_BATCH / dt, GEN_BATCH * GEN_NEW / dt


def _previous_value():
    def round_num(path):
        m = re.search(r'BENCH_r(\d+)\.json$', path)
        return int(m.group(1)) if m else -1

    best = None
    for path in sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), 'BENCH_r*.json')),
            key=round_num):
        try:
            with open(path) as f:
                rec = json.load(f)
            if rec.get('unit', '').startswith('samples/sec'):
                best = rec.get('value', best)
        except Exception:
            pass
    return best


def main():
    n_chips = max(1, len(jax.devices()))
    params = init_params(CFG, jax.random.PRNGKey(0))
    ppl_sps = _bench_ppl(params)
    gen_sps, gen_tps = _bench_gen(params)
    # headline: harmonic-style blend of the two eval paths, per chip
    value = 2.0 / (1.0 / ppl_sps + 1.0 / gen_sps) / n_chips
    prev = _previous_value()
    record = {
        'metric': 'eval samples/sec/chip (PPL b32xs512 + gen b16 p128+64, '
                  'llama-1024x8 bf16)',
        'value': round(value, 3),
        'unit': 'samples/sec/chip',
        'vs_baseline': round(value / prev, 3) if prev else 1.0,
        'detail': {
            'ppl_samples_per_sec': round(ppl_sps, 3),
            'gen_samples_per_sec': round(gen_sps, 3),
            'gen_tokens_per_sec': round(gen_tps, 1),
            'n_chips': n_chips,
            'platform': jax.devices()[0].platform,
        },
    }
    print(json.dumps(record))


if __name__ == '__main__':
    main()
