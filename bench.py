"""Headline benchmark: Llama-7B-class eval throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Workload mirrors the reference's hot loops (SURVEY.md §3.2-3.3) at the
BASELINE north-star scale (Llama-7B geometry, random init):

- PPL scoring: one jitted forward + shifted CE per batch — the MMLU/PIQA
  ranking path.  Headline runs the W8A8 serving config (int8 weights +
  dynamic per-token int8 activations, int8 x int8 on the MXU); the bf16
  figure, achieved TFLOP/s, MFU, and flash on/off are in detail.
- Greedy generation: jitted prefill + while-loop KV-cache decode — the
  GSM8K path.  Headline is the throughput config: batch 128, W8A8
  matmuls, int8 KV cache consumed by the Pallas decode-attention kernel
  (nn/decode_attention.py — the XLA path materializes a bf16 copy of
  the whole cache every step; the kernel reads int8 tiles into VMEM and
  runs both contractions int8 x int8 on the MXU).  bf16 / int8 /
  int4-KV ladder at batch 32/64/128 kept in detail for
  round-over-round continuity.

Quantization accuracy is pinned by tests/test_quant.py (logit closeness,
PPL-rank agreement, decode token agreement vs the bf16 path); modes ship
via ``JaxLM(quantize='w8a8-kv4')`` etc.

``vs_baseline``: the reference publishes no perf numbers (BASELINE.md), so
the baseline is an analytic single-A100-80GB estimate of the reference
stack (HF transformers fp16 on torch.cuda) under generous assumptions:
50% MFU compute for scoring/prefill and an idealized decode that streams
int8 weights at 70% of HBM with KV reads free — a capability envelope the
reference's actual stack (whose int8 path, bitsandbytes, is slower than
fp16 decode in practice) does not reach.  We do not grant it W8A8 MXU
scoring because no such torch eval path exists in the reference; our
headline runs our stack's shipping quantized config, and bf16 figures are
reported alongside (details in `detail.a100_est`).  BASELINE.json's north
star is >=3x single-A100 samples/sec on a v5e-16; tasks are partitioned
per chip (runners/local.py), so 16 chips scale this per-chip number
linearly.

A smaller llama-1024x8 config is also timed for round-over-round
continuity with BENCH_r01 (detail.small).
"""
import dataclasses
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from opencompass_tpu.nn import (TransformerConfig, forward, greedy_generate,
                                init_params, sequence_nll)
from opencompass_tpu.nn.agreement import (eval_pool, forced_decode,
                                          forced_stats, score_pool,
                                          scoring_stats)

CFG_7B = TransformerConfig.llama(
    vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
    num_kv_heads=32, intermediate_size=11008, max_seq_len=2048)

CFG_SMALL = TransformerConfig.llama(
    vocab_size=32000, hidden_size=1024, num_layers=8, num_heads=16,
    num_kv_heads=16, intermediate_size=2816, max_seq_len=2048)

# peak dense bf16 TFLOP/s per chip, for MFU
_PEAK_TFLOPS = {'TPU v5 lite': 197.0, 'TPU v5': 459.0, 'TPU v4': 275.0,
                'TPU v6 lite': 918.0}

PPL_BATCH, PPL_SEQ, PPL_ITERS = 16, 512, 6
GEN_BATCH, GEN_PROMPT, GEN_NEW = 32, 128, 64
GEN_BATCH_HEADLINE = 128  # W8A8 + int8-KV throughput configuration
LONG_SEQ, LONG_BATCH, LONG_ITERS = 2048, 4, 3  # long-context scoring leg
GEN_LONG_PROMPT, GEN_LONG_BATCH = 1024, 16     # long-context gen leg


def _param_count(cfg):
    D, F, L, V = (cfg.hidden_size, cfg.intermediate_size, cfg.num_layers,
                  cfg.vocab_size)
    per_layer = D * (cfg.q_dim + 2 * cfg.kv_dim) + cfg.q_dim * D + 3 * D * F
    return L * per_layer + 2 * V * D


def _blend(a, b):
    """Harmonic blend of the two eval paths (equal sample weight)."""
    return 2.0 / (1.0 / a + 1.0 / b)


def _bench_ppl(params, cfg, iters, use_flash=True, batch=PPL_BATCH,
               seq=PPL_SEQ):
    @jax.jit
    def step(params, tokens, mask):
        logits = forward(params, cfg, tokens, mask, use_flash=use_flash)
        return sequence_nll(logits, tokens, mask)

    tokens = jnp.ones((batch, seq), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.bool_)
    # host fetch (not block_until_ready) to fully drain compile + queue
    np.asarray(step(params, tokens, mask))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(params, tokens, mask)
    np.asarray(out)
    dt = (time.perf_counter() - t0) / iters
    samples_per_sec = batch / dt
    tflops = 2 * _param_count(cfg) * batch * seq / dt / 1e12
    return samples_per_sec, tflops


def _bench_gen(params, cfg, batch=GEN_BATCH, prompt=GEN_PROMPT):
    @jax.jit
    def step(params, tokens, mask):
        return greedy_generate(params, cfg, tokens, mask, GEN_NEW,
                               eos_token_id=None)[0]

    tokens = jnp.ones((batch, prompt), jnp.int32)
    mask = jnp.ones((batch, prompt), jnp.bool_)
    np.asarray(step(params, tokens, mask))  # compile + full sync
    t0 = time.perf_counter()
    out = step(params, tokens, mask)
    np.asarray(out)
    dt = time.perf_counter() - t0
    return batch / dt, batch * GEN_NEW / dt


def _a100_estimate(cfg, gen_batch=GEN_BATCH):
    """Single-A100-80GB blended samples/sec for the reference stack (HF
    transformers fp16 on torch.cuda) under generous assumptions, at the
    SAME generation batch as the measured config.

    Decode is modeled weight-bound at 70% of HBM with int8 weight
    streaming granted (the reference's actual int8 path, bitsandbytes, is
    slower than fp16 in practice) PLUS the KV-cache reads every real
    attention implementation pays, at the fp16 cache dtype the reference
    stack actually uses (HF has no quantized-cache eval path; average
    valid slots over the decode).  W8A8 MXU scoring is likewise not
    granted — no such torch eval path exists in the reference.

    BENCH_r01/r02 modeled KV reads as free; at batch 32 that was a minor
    give (KV ~ half the weight bytes) but at the batch-128 headline KV
    is 1.6x the weight bytes and omitting it is indefensible.
    ``blended_r02_convention`` reports the old formula at batch 32 so the
    round-over-round series stays traceable.
    """
    n = _param_count(cfg)
    peak, hbm = 312e12, 2.039e12
    eff_hbm = 0.7 * hbm
    ppl_sps = 0.5 * peak / (2 * n * PPL_SEQ)
    prefill = 2 * n * gen_batch * GEN_PROMPT / (0.5 * peak)
    # fp16 K+V reads per step, averaged over the decode's fill level
    avg_slots = GEN_PROMPT + GEN_NEW / 2
    kv_step = (2 * cfg.num_layers * cfg.kv_dim * avg_slots * gen_batch
               * 2) / eff_hbm
    decode_bf16 = GEN_NEW * ((2 * n) / eff_hbm + kv_step)
    decode_int8 = GEN_NEW * (n / eff_hbm + kv_step)
    gen_sps_bf16 = gen_batch / (prefill + decode_bf16)
    gen_sps = gen_batch / (prefill + decode_int8)
    prefill_b32 = 2 * n * GEN_BATCH * GEN_PROMPT / (0.5 * peak)
    gen_r02 = GEN_BATCH / (prefill_b32 + GEN_NEW * n / eff_hbm)
    return {
        'blended': _blend(ppl_sps, gen_sps),
        'blended_bf16': _blend(ppl_sps, gen_sps_bf16),
        'blended_r02_convention': _blend(ppl_sps, gen_r02),
        'gen_batch': gen_batch,
        'ppl_samples_per_sec': round(ppl_sps, 2),
        'gen_samples_per_sec': round(gen_sps, 2),
        'gen_bf16_samples_per_sec': round(gen_sps_bf16, 2),
        'assumptions': 'A100-80GB SXM, 312 TFLOP/s bf16 at 50% MFU, '
                       'decode at 70% of 2.04 TB/s HBM streaming int8 '
                       'weights (granted despite bitsandbytes being '
                       'slower than fp16 in practice) + fp16 KV-cache '
                       'reads at average fill; W8A8 MXU scoring NOT '
                       'granted (no such torch eval path exists in the '
                       'reference)',
    }


TRAJECTORY_JSON = 'BENCH_TRAJECTORY.json'


def _append_trajectory(leg, metric, value, unit, direction='higher',
                       detail=None):
    """Append one normalized record to ``BENCH_TRAJECTORY.json`` (a JSON
    array) so the per-PR perf trajectory accumulates round over round;
    ``cli ledger check --trajectory BENCH_TRAJECTORY.json`` gates the
    latest value against the previous one.  ``direction`` says which way
    is better ('higher' for speedups/hit rates, 'lower' for seconds).
    Never raises — the bench numbers still print when the file is
    unwritable."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        TRAJECTORY_JSON)
    try:
        try:
            with open(path, encoding='utf-8') as f:
                records = json.load(f)
            if not isinstance(records, list):
                records = []
        except (OSError, ValueError):
            records = []
        records.append({'v': 1, 'ts': round(time.time(), 3), 'leg': leg,
                        'metric': metric, 'value': value, 'unit': unit,
                        'direction': direction, 'detail': detail})
        from opencompass_tpu.utils.fileio import atomic_write_json
        atomic_write_json(path, records, dump_kwargs={'indent': 2,
                                                      'default': str})
    except Exception:
        pass


def _bench_planner():
    """Host-only batch-planner leg (icl/inferencers/schedule.py): padding
    efficiency and distinct jit-shape count, planned vs sequential
    chunking, on a skewed MMLU-like arrival order (subject-clustered
    short/medium prompts with long few-shot outliers sprinkled through).
    No device involved — this measures the scheduler, and regressions
    here show up before any TPU time is spent."""
    import random

    from opencompass_tpu.icl.inferencers import schedule
    from opencompass_tpu.models.jax_lm import _bucket

    def shape_fn(n, longest):
        return _bucket(max(n, 1), lo=1), _bucket(max(longest, 1), hi=2048)

    rng = random.Random(3)
    lengths = []
    for block in range(8):
        lo, hi = (70, 128) if block % 2 == 0 else (300, 500)
        lengths += [rng.randint(lo, hi) for _ in range(46)]
    for _ in range(24):
        lengths.insert(rng.randrange(len(lengths)),
                       rng.randint(1400, 1900))
    t0 = time.perf_counter()
    planned = schedule.plan_batches(lengths, 16, shape_fn=shape_fn)
    plan_ms = (time.perf_counter() - t0) * 1e3
    seq = schedule.sequential_plan(lengths, 16, shape_fn=shape_fn)
    return {
        'workload': '8 length-clustered blocks of 46 + 24 long outliers, '
                    'batch 16',
        'pad_eff_planned': round(planned.stats.pad_eff, 4),
        'pad_eff_sequential': round(seq.stats.pad_eff, 4),
        'pad_eff_speedup': round(
            planned.stats.pad_eff / seq.stats.pad_eff, 2),
        'shapes_planned': planned.stats.n_shapes,
        'shapes_sequential': seq.stats.n_shapes,
        'batches_planned': planned.stats.n_batches,
        'batches_sequential': seq.stats.n_batches,
        'plan_ms': round(plan_ms, 2),
    }


def _warm_path_child(cache_dir):
    """One cold-start 'task': fresh interpreter, build a tiny JaxLM, run
    one scoring + one generation batch against the persistent compile
    cache at ``cache_dir``.  Prints the TaskProfiler perf record (plus
    model-build seconds) as one JSON line for the parent to diff."""
    os.environ['OCT_COMPILE_CACHE'] = cache_dir
    from opencompass_tpu.models.jax_lm import JaxLM
    from opencompass_tpu.utils import compile_cache
    from opencompass_tpu.utils.perf import TaskProfiler
    compile_cache.enable()
    t0 = time.perf_counter()
    lm = JaxLM(config='tiny', max_seq_len=256)
    build_s = time.perf_counter() - t0
    with TaskProfiler(lm) as prof:
        lm.get_ppl(['the quick brown fox jumps over the lazy dog',
                    'pack my box with five dozen liquor jugs'])
        lm.generate(['warm path check'], max_out_len=8)
    rec = dict(prof.record)
    rec['model_build_seconds'] = round(build_s, 3)
    print(json.dumps(rec))


def _bench_worker_pool():
    """Worker-mode FakeModel leg: N dataset shards through ONE
    model-resident worker — asserts the residency story end to end
    (model built exactly once, every shard green) and times it."""
    import os.path as osp
    import tempfile

    from opencompass_tpu import obs
    from opencompass_tpu.config import Config
    from opencompass_tpu.partitioners import SizePartitioner
    from opencompass_tpu.runners import LocalRunner

    work = tempfile.mkdtemp(prefix='oct_warm_worker_')
    cfg = Config.fromfile(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     'configs/eval_demo.py'))
    cfg['work_dir'] = work
    cfg['obs'] = True
    obs.reset_obs()
    tracer = obs.init_obs(work, enabled=True)
    part = SizePartitioner(osp.join(work, 'predictions/'),
                           max_task_size=100,
                           dataset_size_path=osp.join(work, 'size.json'))
    tasks = part(cfg)
    t0 = time.perf_counter()
    runner = LocalRunner(task=dict(type='OpenICLInferTask'),
                         use_workers=True, max_num_workers=4)
    status = runner(tasks)
    wall = time.perf_counter() - t0
    tracer.close()
    builds = 0
    with open(osp.join(work, 'obs', 'events.jsonl')) as f:
        for line in f:
            if '"worker_model_build"' in line:
                builds += 1
    obs.reset_obs()
    return {
        'n_tasks': len(tasks),
        'model_builds': builds,
        'failed': sum(1 for _, rc in status if rc != 0),
        'wall_seconds': round(wall, 2),
    }


def _bench_warm_path(out_json='BENCH_WARM.json'):
    """detail.warm_path: the same tiny-JaxLM task twice, each a fresh
    interpreter, sharing one persistent XLA compile cache — the
    second run's compile_seconds is the warm-path win (cache retrieval
    instead of cold compiles) — plus the worker-pool residency leg.
    The record is also written to ``BENCH_WARM.json`` so the perf
    trajectory accumulates round over round."""
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix='oct_warm_cache_')
    here = os.path.abspath(__file__)
    runs = []
    for _ in range(2):
        r = subprocess.run(
            [sys.executable, here, '--warm-path-child', cache_dir],
            capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(here))
        if r.returncode != 0:
            return {'error': (r.stderr or r.stdout)[-500:]}
        runs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    cold, warm = runs
    record = {
        'v': 1,
        'workload': 'tiny JaxLM (s256): 1 ppl + 1 gen batch per run, '
                    'two fresh processes sharing one compile cache',
        'cold': cold,
        'warm': warm,
        'compile_seconds_cold': cold.get('compile_seconds'),
        'compile_seconds_warm': warm.get('compile_seconds'),
        'compile_speedup': round(
            cold.get('compile_seconds', 0.0)
            / max(warm.get('compile_seconds', 0.0), 1e-3), 2),
        'wall_delta_seconds': round(
            cold.get('wall_seconds', 0.0) - warm.get('wall_seconds',
                                                     0.0), 3),
        'cache_hits_warm': warm.get('compile_cache_hits'),
        'cache_misses_cold': cold.get('compile_cache_misses'),
        'worker_pool': _bench_worker_pool(),
    }
    try:
        with open(os.path.join(os.path.dirname(here), out_json),
                  'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    _append_trajectory(
        'warm_path', 'compile_speedup', record.get('compile_speedup'),
        'x', detail={'cold_s': record.get('compile_seconds_cold'),
                     'warm_s': record.get('compile_seconds_warm')})
    return record


def _result_cache_child(cache_root, work_dir):
    """One infer sweep of the demo config (fresh interpreter) against
    the shared result-store cache root, with obs on.  Prints one JSON
    line: wall, task count, device batches executed, store activity —
    the parent diffs cold vs warm."""
    import os.path as osp

    os.environ['OCT_CACHE_ROOT'] = cache_root
    from opencompass_tpu import obs
    from opencompass_tpu.config import Config
    from opencompass_tpu.partitioners import SizePartitioner
    from opencompass_tpu.runners import LocalRunner
    cfg = Config.fromfile(
        osp.join(osp.dirname(osp.abspath(__file__)),
                 'configs/eval_demo.py'))
    cfg['work_dir'] = work_dir
    cfg['obs'] = True
    tracer = obs.init_obs(work_dir, enabled=True)
    t0 = time.perf_counter()
    part = SizePartitioner(osp.join(work_dir, 'predictions/'),
                           dataset_size_path=osp.join(work_dir,
                                                      'size.json'))
    tasks = part(cfg)
    failed = 0
    if tasks:
        status = LocalRunner(task=dict(type='OpenICLInferTask'),
                             debug=True)(tasks)
        failed = sum(1 for _, rc in status if rc != 0)
    wall = time.perf_counter() - t0
    tracer.flush_metrics()
    tracer.close()
    counters = {}
    with open(osp.join(work_dir, 'obs', 'events.jsonl')) as f:
        for line in f:
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if ev.get('kind') == 'metrics':
                counters = (ev.get('attrs') or {}).get('counters') or {}
    batches = sum(counters.get(k, 0)
                  for k in ('inferencer.gen_batches',
                            'inferencer.ppl_batches',
                            'inferencer.clp_batches'))
    print(json.dumps({
        'wall_seconds': round(wall, 3), 'n_tasks': len(tasks),
        'failed': failed, 'device_batches': batches,
        'store_hits': counters.get('store.hits', 0),
        'store_misses': counters.get('store.misses', 0),
        'store_commits': counters.get('store.commits', 0),
        'pruned_rows': counters.get('store.pruned_rows', 0),
    }))


def _bench_result_cache(out_json='BENCH_STORE.json'):
    """detail.result_cache: the same FakeModel sweep three times, each a
    fresh interpreter, sharing one result store:

    - cold: empty store, every row executes and commits;
    - warm_prune: identical rerun — the partitioner materializes every
      prediction file pre-launch and emits ZERO tasks;
    - warm_rows: unit manifests removed — tasks launch but every row is
      served from the store (zero device batches).

    Written to ``BENCH_STORE.json`` so the perf trajectory accumulates
    round over round."""
    import shutil
    import subprocess
    import tempfile

    cache_root = tempfile.mkdtemp(prefix='oct_store_cache_')
    here = os.path.abspath(__file__)

    def child(tag):
        work = tempfile.mkdtemp(prefix=f'oct_store_{tag}_')
        r = subprocess.run(
            [sys.executable, here, '--result-cache-child', cache_root,
             work],
            capture_output=True, text=True, timeout=900,
            cwd=os.path.dirname(here),
            env=dict(os.environ, JAX_PLATFORMS='cpu'))
        if r.returncode != 0:
            return {'error': (r.stderr or r.stdout)[-500:]}
        return json.loads(r.stdout.strip().splitlines()[-1])

    cold = child('cold')
    warm_prune = child('warm_prune')
    if 'error' not in warm_prune:
        shutil.rmtree(os.path.join(cache_root, 'store', 'units'),
                      ignore_errors=True)
    warm_rows = child('warm_rows')
    hits = warm_rows.get('store_hits', 0)
    misses = warm_rows.get('store_misses', 0)
    record = {
        'v': 1,
        'workload': 'FakeModel demo sweep (gen 16 rows + ppl 8x2 rows), '
                    'three fresh processes sharing one result store',
        'cold': cold,
        'warm_prune': warm_prune,
        'warm_rows': warm_rows,
        'cold_batches': cold.get('device_batches'),
        'warm_rows_batches': warm_rows.get('device_batches'),
        'warm_rows_hit_rate': round(hits / (hits + misses), 4)
        if hits + misses else None,
        'prune_tasks_cold_vs_warm': [cold.get('n_tasks'),
                                     warm_prune.get('n_tasks')],
        'wall_speedup_prune': round(
            cold.get('wall_seconds', 0.0)
            / max(warm_prune.get('wall_seconds', 0.0), 1e-3), 2),
        'wall_speedup_rows': round(
            cold.get('wall_seconds', 0.0)
            / max(warm_rows.get('wall_seconds', 0.0), 1e-3), 2),
    }
    try:
        with open(os.path.join(os.path.dirname(here), out_json),
                  'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    _append_trajectory(
        'result_cache', 'warm_rows_hit_rate',
        record.get('warm_rows_hit_rate'), 'fraction',
        detail={'cold_batches': record.get('cold_batches'),
                'warm_rows_batches': record.get('warm_rows_batches')})
    return record


def _bench_flight_recorder(out_json='BENCH_FLIGHT.json'):
    """detail.flight_recorder: one FakeModel demo sweep with the flight
    recorder on — asserts the observability contract end to end (per-
    batch timeline files written, Chrome export well-formed, a ledger
    record appended) and records the recorder's measured overhead-free
    throughput.  Device-free; runs on CPU hosts."""
    import os.path as osp
    import tempfile

    from opencompass_tpu import ledger, obs
    from opencompass_tpu.config import Config
    from opencompass_tpu.obs.export import build_chrome_trace
    from opencompass_tpu.obs.timeline import summarize_timelines
    from opencompass_tpu.partitioners import SizePartitioner
    from opencompass_tpu.runners import LocalRunner

    work = tempfile.mkdtemp(prefix='oct_flight_')
    cache_root = osp.join(work, 'cache')
    prev_root = os.environ.get('OCT_CACHE_ROOT')
    os.environ['OCT_CACHE_ROOT'] = cache_root
    cfg = Config.fromfile(
        osp.join(osp.dirname(osp.abspath(__file__)),
                 'configs/eval_demo.py'))
    cfg['work_dir'] = work
    cfg['obs'] = True
    cfg['result_cache'] = False   # every row must execute and record
    obs.reset_obs()
    tracer = obs.init_obs(work, enabled=True)
    part = SizePartitioner(osp.join(work, 'predictions/'),
                           dataset_size_path=osp.join(work, 'size.json'))
    tasks = part(cfg)
    t0 = time.perf_counter()
    status = LocalRunner(task=dict(type='OpenICLInferTask'),
                         debug=True)(tasks)
    wall = time.perf_counter() - t0
    tracer.close()
    summaries = summarize_timelines(tracer.obs_dir)
    doc = build_chrome_trace(work)
    ledger_records = ledger.append_run(work, run_id='bench_flight')
    obs.reset_obs()
    if prev_root is None:
        os.environ.pop('OCT_CACHE_ROOT', None)
    else:
        os.environ['OCT_CACHE_ROOT'] = prev_root
    batches = sum(s.get('batches', 0) for s in summaries.values())
    tps = [s['tokens_per_sec'] for s in summaries.values()
           if s.get('tokens_per_sec')]
    record = {
        'v': 1,
        'workload': 'FakeModel demo sweep, --obs flight recorder on '
                    '(timeline + Chrome export + ledger record)',
        'n_tasks': len(tasks),
        'failed': sum(1 for _, rc in status if rc != 0),
        'wall_seconds': round(wall, 3),
        'timeline_files': len(summaries),
        'timeline_batches': batches,
        'export_events': len(doc.get('traceEvents') or []),
        'ledger_records': len(ledger_records),
        'tokens_per_sec_mean': round(sum(tps) / len(tps), 1)
        if tps else None,
    }
    try:
        with open(os.path.join(
                os.path.dirname(os.path.abspath(__file__)), out_json),
                'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    _append_trajectory(
        'flight_recorder', 'timeline_batches', batches, 'batches',
        detail={'export_events': record['export_events'],
                'ledger_records': record['ledger_records']})
    return record


def _bench_continuous(out_json='BENCH_DECODE.json'):
    """detail.continuous_batching: the continuous-batching decode engine
    vs the fixed-shape ``lax.while_loop`` path on a skewed-length gen
    workload (tiny JaxLM, CPU-runnable).

    Skew is the serving-realistic kind: mixed prompt lengths AND mixed
    decode budgets (4/8/32 new tokens).  The fixed-shape path must run
    each (B×S bucket, max_new) combination as its own compiled
    executable and every row in a batch waits for the batch's longest;
    the engine runs ONE decode shape (slots×1) + ONE prefill-chunk
    shape, rows join as others retire, and each row pays only its own
    tokens.  Since the mixed-step PR the engine compiles ONE fused
    prefill+decode executable (was two).  Asserts greedy token-identity
    between the two paths and exactly one mixed shape in the
    compile-cache manifest."""
    import tempfile

    from opencompass_tpu.models import JaxLM
    from opencompass_tpu.utils import compile_cache
    from opencompass_tpu.utils.compile_cache import load_manifest

    cache_dir = tempfile.mkdtemp(prefix='oct_cont_cache_')
    os.environ['OCT_COMPILE_CACHE'] = cache_dir
    compile_cache.enable()

    rng = np.random.RandomState(7)
    prompts = []
    budgets = []
    for i in range(20):
        n_words = int(rng.choice([3, 6, 12, 40, 90]))
        prompts.append(' '.join(
            f'w{rng.randint(999)}' for _ in range(n_words)))
        budgets.append(int(rng.choice([4, 4, 8, 8, 8, 32])))

    # -- fixed-shape path: group rows by decode budget (as a sweep of
    # per-task max_out_len values would), sub-batch at 8
    lm_fixed = JaxLM(config='tiny', max_seq_len=256)
    fixed_texts = [None] * len(prompts)
    fixed_lat = [None] * len(prompts)
    t0 = time.perf_counter()
    by_budget = {}
    for i, b in enumerate(budgets):
        by_budget.setdefault(b, []).append(i)
    for b, idxs in sorted(by_budget.items()):
        for lo in range(0, len(idxs), 8):
            chunk = idxs[lo:lo + 8]
            outs = lm_fixed.generate([prompts[i] for i in chunk],
                                     max_out_len=b)
            done = time.perf_counter() - t0
            for i, out in zip(chunk, outs):
                fixed_texts[i] = out
                fixed_lat[i] = done
    fixed_wall = time.perf_counter() - t0
    fixed_tokens = lm_fixed.perf.tokens_out
    fixed_gen_shapes = sorted(
        {k[1:] for k in lm_fixed._dispatched_keys if k[0] == 'gen'})

    # -- continuous engine: every row enters the feed queue with its own
    # budget; rows join the resident step as slots free up
    lm_cont = JaxLM(config='tiny', max_seq_len=256,
                    continuous_batching=True, decode_slots=4,
                    kv_page_size=32)
    engine = lm_cont.continuous_engine()
    cap = lm_cont.max_seq_len
    ids = [lm_cont._encode_ids(p) for p in prompts]
    ids = [r[:max(cap - b, 32)] for r, b in zip(ids, budgets)]
    cont_texts = [None] * len(prompts)
    cont_lat = [None] * len(prompts)
    t0 = time.perf_counter()
    order = sorted(range(len(ids)), key=lambda i: (-len(ids[i]), i))
    rows = [engine.submit(ids[i], budgets[i], tag=i) for i in order]

    def deliver(row):
        toks = [t for t in row.emitted if t != lm_cont.eos_token_id] \
            if lm_cont.eos_token_id is not None else row.emitted
        cont_texts[row.tag] = lm_cont.tokenizer.decode(toks)
        cont_lat[row.tag] = time.perf_counter() - t0

    engine.drain(rows, deliver)
    cont_wall = time.perf_counter() - t0
    cont_tokens = sum(len(r.emitted) for r in rows)
    sig = lm_cont.shape_signature
    manifest = load_manifest(cache_dir).get(sig, {})
    engine_shapes = sorted(k for k in manifest
                           if k.startswith(('mixed:', 'decode:',
                                            'prefill_chunk:')))

    identical = fixed_texts == cont_texts

    def p95(vals):
        # nearest-rank: ceil(q*n)-1 (same convention as reqtrace's
        # rolling-window percentiles)
        vals = sorted(vals)
        return vals[max(0, -(-95 * len(vals) // 100) - 1)]

    fixed_tps = fixed_tokens / max(fixed_wall, 1e-9)
    cont_tps = cont_tokens / max(cont_wall, 1e-9)
    record = {
        'v': 1,
        'workload': '20 rows, prompt words in {3..90}, decode budgets '
                    '{4,8,32}, tiny JaxLM (CPU); fixed path groups by '
                    'budget at batch 8, engine runs 4 slots / page 32',
        'rows': len(prompts),
        'decode_tokens_fixed': int(fixed_tokens),
        'decode_tokens_continuous': int(cont_tokens),
        'fixed_wall_seconds': round(fixed_wall, 3),
        'continuous_wall_seconds': round(cont_wall, 3),
        'fixed_tokens_per_sec': round(fixed_tps, 1),
        'continuous_tokens_per_sec': round(cont_tps, 1),
        'tokens_per_sec_speedup': round(cont_tps / max(fixed_tps, 1e-9),
                                        2),
        'fixed_row_latency_p95_s': round(p95(fixed_lat), 3),
        'continuous_row_latency_p95_s': round(p95(cont_lat), 3),
        'fixed_gen_compile_shapes': len(fixed_gen_shapes),
        'continuous_compile_shapes': len(engine_shapes),
        'engine_manifest_shapes': engine_shapes,
        'stall_slot_steps': engine.stats()['stall_slot_steps'],
        'kv_read_path': engine.stats()['kv_read_path'],
        'slot_util': engine.stats()['slot_util'],
        'greedy_identical': bool(identical),
    }
    assert identical, 'continuous outputs diverged from fixed-shape path'
    # ONE fused mixed executable — the legacy decode/prefill_chunk pair
    # must not appear in the manifest
    assert len(engine_shapes) == 1 \
        and engine_shapes[0].startswith('mixed:'), engine_shapes
    assert record['stall_slot_steps'] == 0
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, out_json), 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    _append_trajectory(
        'continuous_batching', 'tokens_per_sec_speedup',
        record['tokens_per_sec_speedup'], 'x', direction='higher',
        detail={'fixed_tokens_per_sec': record['fixed_tokens_per_sec'],
                'continuous_tokens_per_sec':
                    record['continuous_tokens_per_sec'],
                'row_latency_p95_s':
                    record['continuous_row_latency_p95_s'],
                'slot_util': record['slot_util'],
                'compile_shapes': len(engine_shapes),
                'stall_slot_steps': record['stall_slot_steps'],
                'kv_read_path': record['kv_read_path'],
                'engine_manifest_shapes': engine_shapes})
    return record


def _bench_prefix(out_json='BENCH_PREFIX.json'):
    """detail.prefix_cache: radix prefix cache + draft-model speculative
    decoding over the paged engine (tiny JaxLM, CPU-runnable).

    Workload is the few-shot eval shape the cache targets: one shared
    ICE block (~75%% of prompt tokens) + short per-item remainders.
    Leg 1 runs the same sweep with the trie off and on and asserts the
    trie (a) halves prefill tokens (the ISSUE floor is a 50%% drop at
    >=70%% share) and (b) leaves outputs byte-identical.  Leg 2 runs
    draft-model speculative decoding (same tiny config as draft) and
    asserts greedy token-identity to the plain engine while reporting
    the acceptance rate and tokens/s."""
    from opencompass_tpu.models import JaxLM

    shared = ('Q: what color is the sky above the sea at noon? '
              'A: blue. ' * 12)
    rng = np.random.RandomState(11)
    prompts = [shared + 'Q: item ' + ' '.join(
        f'w{rng.randint(999)}' for _ in range(rng.randint(2, 6)))
        + '? A:' for i in range(16)]

    kw = dict(config='tiny', max_seq_len=512, continuous_batching=True,
              decode_slots=4, kv_page_size=16)

    # -- leg 1: trie off vs on, identical greedy sweep
    lm_off = JaxLM(**kw)
    t0 = time.perf_counter()
    out_off = lm_off.generate_continuous(prompts, 8)
    off_wall = time.perf_counter() - t0
    eng_off = lm_off.continuous_engine()
    off_prefill = int(eng_off.prefill_tokens)

    lm_on = JaxLM(prefix_cache=True, **kw)
    t0 = time.perf_counter()
    out_on = lm_on.generate_continuous(prompts, 8)
    on_wall = time.perf_counter() - t0
    eng_on = lm_on.continuous_engine()
    st = eng_on.stats()
    on_prefill = int(eng_on.prefill_tokens)
    saved = int(st['prefill_tokens_saved'])
    saved_frac = saved / max(saved + on_prefill, 1)
    share = saved / max(off_prefill, 1)

    # -- leg 2: speculative decoding, identity vs the plain engine
    lm_spec = JaxLM(draft_model=dict(config='tiny', max_seq_len=512),
                    draft_k=4, **kw)
    assert lm_spec.speculative_active, 'spec engine did not activate'
    t0 = time.perf_counter()
    out_spec = lm_spec.generate_continuous(prompts, 24)
    spec_wall = time.perf_counter() - t0
    sst = lm_spec.continuous_engine().stats()
    t0 = time.perf_counter()
    out_ref = lm_off.generate_continuous(prompts, 24)
    ref_wall = time.perf_counter() - t0
    ref_tokens = sum(
        len(lm_off._encode_ids(o)) for o in out_ref)

    record = {
        'v': 1,
        'workload': f'{len(prompts)} rows, shared ICE block '
                    f'({share:.0%} of prefill tokens), tiny JaxLM '
                    '(CPU); 4 slots / page 16',
        'rows': len(prompts),
        'prefill_tokens_off': off_prefill,
        'prefill_tokens_on': on_prefill,
        'prefill_tokens_saved': saved,
        'prefill_tokens_saved_frac': round(saved_frac, 4),
        'prefix_hits': int(st['prefix_hits']),
        'prefix_cow_copies': int(st['prefix_cow_copies']),
        'trie': st['prefix_cache'],
        'off_wall_seconds': round(off_wall, 3),
        'on_wall_seconds': round(on_wall, 3),
        'greedy_identical': bool(out_on == out_off),
        'spec': {
            'draft_k': 4,
            'proposed': int(sst['spec_proposed']),
            'accepted': int(sst['spec_accepted']),
            'accept_rate': round(sst['spec_accept_rate'] or 0.0, 4),
            'decode_tokens': int(sst['decode_tokens']),
            'wall_seconds': round(spec_wall, 3),
            'tokens_per_sec': round(
                sst['decode_tokens'] / max(spec_wall, 1e-9), 1),
            'ref_tokens_per_sec': round(
                ref_tokens / max(ref_wall, 1e-9), 1),
            'greedy_identical': bool(out_spec == out_ref),
        },
    }
    assert record['greedy_identical'], \
        'prefix-cache outputs diverged from the trie-off sweep'
    assert on_prefill <= 0.5 * off_prefill, (
        f'trie saved only {saved_frac:.1%} of prefill tokens '
        f'({on_prefill} vs {off_prefill})')
    assert record['spec']['greedy_identical'], \
        'speculative outputs diverged from the plain engine'
    assert record['spec']['proposed'] > 0
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, out_json), 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    _append_trajectory(
        'prefix', 'prefill_tokens_saved_frac',
        record['prefill_tokens_saved_frac'], 'frac', direction='higher',
        detail={'prefill_tokens_off': off_prefill,
                'prefill_tokens_on': on_prefill,
                'prefix_hits': record['prefix_hits'],
                'prefix_cow_copies': record['prefix_cow_copies'],
                'greedy_identical': record['greedy_identical']})
    _append_trajectory(
        'spec', 'accept_rate',
        record['spec']['accept_rate'], 'frac', direction='higher',
        detail={'draft_k': record['spec']['draft_k'],
                'proposed': record['spec']['proposed'],
                'accepted': record['spec']['accepted'],
                'tokens_per_sec': record['spec']['tokens_per_sec'],
                'greedy_identical': record['spec']['greedy_identical']})
    return record


def _bench_lint(out_json='BENCH_LINT.json'):
    """detail.lint: oct-lint coverage smoke over the package — files
    scanned, per-rule finding counts, pragma/baseline triage state
    (docs/static_analysis.md).  Written to BENCH_LINT.json so lint
    coverage (and any drift toward wholesale suppression) is tracked
    per PR next to the perf legs.  Device-free."""
    import time as _time
    from opencompass_tpu.analysis.linter import run_lint
    t0 = _time.perf_counter()
    report = run_lint()
    record = {
        'v': 1,
        'files_scanned': report.files_scanned,
        'findings_active': len(report.active),
        'findings_baselined': len(report.baselined),
        'pragmas': report.pragma_count,
        'by_rule': report.by_rule(),
        'stale_baseline': len(report.stale_baseline),
        'parse_errors': len(report.parse_errors),
        'clean': not report.active and not report.parse_errors,
        'lint_seconds': round(_time.perf_counter() - t0, 3),
    }
    if out_json:
        with open(out_json, 'w') as fh:
            json.dump(record, fh, indent=2)
            fh.write('\n')
    return record


def _bench_roofline(out_json='BENCH_ROOFLINE.json'):
    """detail.roofline: MFU/MBU attribution (obs/costmodel.py) for a
    dense fixed-shape gen leg and TWO continuous-batching engine legs
    on the tiny JaxLM (CPU-runnable): the XLA paged-gather fallback and
    the Pallas ragged-paged-attention kernel (interpret mode off-TPU —
    identical read accounting, exact kernel semantics).  Each engine
    leg's flight-recorder record carries the analytic cost fields end
    to end; the actual-vs-ideal KV-traffic ratio is the number the
    kernel exists to close (gather read 8.64x the ragged ideal when
    this leg first pinned it) — the kernel leg must hold it near 1
    (<= 1.5, page-rounding only), gated on the trajectory."""
    import tempfile

    from opencompass_tpu import obs
    from opencompass_tpu.models.jax_lm import JaxLM
    from opencompass_tpu.obs import timeline as tmod
    from opencompass_tpu.obs.costmodel import CostModel

    work = tempfile.mkdtemp(prefix='oct_roofline_')
    obs.reset_obs()
    obs.init_obs(work)
    tl = obs.init_task_timeline('roofline-bench')

    rng = np.random.RandomState(11)
    # serving-realistic fill: prompts occupy a modest fraction of the
    # 512-token context, so the gather's full-table-width reads are
    # visibly wasteful vs the ragged ideal (the usual serving shape)
    prompts = [' '.join(f'w{rng.randint(999)}' for _ in range(int(n)))
               for n in rng.choice([3, 6, 12, 20], size=12)]
    max_new = 16

    # -- dense fixed-shape leg: one padded generate; analytic cost from
    # the same model the batch recorder would use
    lm = JaxLM(config='tiny', max_seq_len=512)
    cm = CostModel.for_model(lm)
    lens = [lm.get_token_len(p) for p in prompts]
    _, S = lm.plan_shape(len(prompts), max(lens),
                         max_len=lm.max_seq_len - max_new)
    snap = lm.perf.snapshot()
    dense_texts = lm.generate(prompts, max_out_len=max_new)
    d = lm.perf.delta_since(snap)
    dense_cost = cm.gen_cost(d['tokens_in'], d['tokens_out'],
                             len(prompts), cache_width=S + max_new)
    dense_secs = d['device_seconds']
    dense_mfu = cm.mfu(dense_cost.flops, dense_secs)
    dense_mbu = cm.mbu(dense_cost.bytes_total, dense_secs)

    # -- continuous-batching leg: the engine's drain record carries the
    # cost fields through the flight recorder (the wired path)
    lm_cont = JaxLM(config='tiny', max_seq_len=512,
                    continuous_batching=True, decode_slots=4,
                    kv_page_size=32)
    cont_texts = lm_cont.generate_continuous(prompts, max_new)
    records = list(tmod.iter_records(tl.path))
    engines = [r for r in records if r.get('t') == 'engine']
    assert engines, 'engine drain left no flight-recorder record'
    eng = engines[-1]
    assert dense_texts == cont_texts, 'greedy identity broke'
    kv_ratio = None
    if eng.get('bytes_kv_ideal'):
        kv_ratio = round(eng['bytes_kv'] / eng['bytes_kv_ideal'], 3)
    assert kv_ratio is not None and kv_ratio > 1.0, (
        'paged-gather KV traffic should exceed the ragged ideal '
        f'(got {kv_ratio})')

    # -- ragged-kernel leg: same workload, KV read through the Pallas
    # kernel (page-granular reads; page 16 keeps the rounding slack
    # small against these prompt+decode extents).  data=1 pins a
    # single-device mesh — the kernel's CPU routing requirement.
    lm_rk = JaxLM(config='tiny', max_seq_len=512,
                  continuous_batching=True, decode_slots=4,
                  kv_page_size=16, ragged_kernel='on',
                  parallel={'data': 1})
    rk_texts = lm_rk.generate_continuous(prompts, max_new)
    records = list(tmod.iter_records(tl.path))
    rk_eng = [r for r in records if r.get('t') == 'engine'][-1]
    obs.reset_obs()
    assert rk_texts == dense_texts, 'kernel-path greedy identity broke'
    assert rk_eng.get('kv_read_path') == 'ragged_kernel'
    kv_ratio_kernel = None
    if rk_eng.get('bytes_kv_ideal'):
        kv_ratio_kernel = round(
            rk_eng['bytes_kv'] / rk_eng['bytes_kv_ideal'], 3)
    assert kv_ratio_kernel is not None and kv_ratio_kernel <= 1.5, (
        'ragged-kernel KV traffic should be page-rounding away from '
        f'the ideal (got {kv_ratio_kernel})')
    record = {
        'v': 1,
        'workload': '12 rows, prompt words in {3..20}, max_new 16, '
                    'tiny JaxLM at max_seq_len 512; dense padded '
                    'batch vs engine (4 slots / page 32)',
        'peaks': {'flops_per_s': cm.peaks.flops_per_s,
                  'bytes_per_s': cm.peaks.bytes_per_s,
                  'source': cm.peaks.source},
        'dense': {
            'device_seconds': round(dense_secs, 3),
            'flops': int(dense_cost.flops),
            'bytes_w': int(dense_cost.bytes_w),
            'bytes_kv': int(dense_cost.bytes_kv),
            'mfu': round(dense_mfu, 6) if dense_mfu else None,
            'mbu': round(dense_mbu, 6) if dense_mbu else None,
        },
        'continuous': {
            'device_seconds': eng.get('device_seconds'),
            'prefill_steps': eng.get('prefill_steps'),
            'decode_steps': eng.get('decode_steps'),
            'flops': eng.get('flops'),
            'bytes_w': eng.get('bytes_w'),
            'bytes_kv': eng.get('bytes_kv'),
            'bytes_kv_ideal': eng.get('bytes_kv_ideal'),
            'mfu': eng.get('mfu'),
            'mbu': eng.get('mbu'),
        },
        'ragged_kernel': {
            'kv_read_path': rk_eng.get('kv_read_path'),
            'page_size': 16,
            'device_seconds': rk_eng.get('device_seconds'),
            'bytes_kv': rk_eng.get('bytes_kv'),
            'bytes_kv_ideal': rk_eng.get('bytes_kv_ideal'),
            'page_read_positions': rk_eng.get('page_read_positions'),
        },
        'kv_traffic_ratio_gather': kv_ratio,
        'kv_traffic_ratio': kv_ratio_kernel,
        'greedy_identical': True,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, out_json), 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    # the MBU series rides the trajectory gate with the same
    # noise-tolerant threshold as the other CPU-timed legs; the KV
    # ratio is pure arithmetic (deterministic), gated tighter by the
    # same invocation
    if eng.get('mbu') is not None:
        _append_trajectory(
            'roofline', 'mbu', eng['mbu'], 'frac', direction='higher',
            detail={'dense_mbu': record['dense']['mbu'],
                    'kv_traffic_ratio_gather': kv_ratio,
                    'peaks_source': cm.peaks.source})
    # the gated series is the ACTIVE read path's ratio: the ragged
    # kernel's page-rounded traffic against the ideal (the gather
    # fallback's 8.64x rides along in detail for the attribution)
    _append_trajectory(
        'roofline', 'kv_traffic_ratio', kv_ratio_kernel, 'x',
        direction='lower',
        detail={'kv_read_path': rk_eng.get('kv_read_path'),
                'kv_traffic_ratio_gather': kv_ratio,
                'page_read_positions':
                    rk_eng.get('page_read_positions'),
                'kv_positions': rk_eng.get('kv_positions')})
    return record


def _bench_devprof(out_json='BENCH_DEVPROF.json'):
    """detail.devprof: the device introspection layer end to end on the
    tiny JaxLM (CPU-runnable) — every fresh executable (ppl scoring +
    the engine's fused mixed step) leaves a compile-audit record with
    XLA's own cost/memory analysis, the measured-vs-modeled flop drift
    is summarized, and step profiling attributes the gather share of
    decode step wall.  Trajectory series gate the deterministic
    numbers: ``model_drift`` is pure arithmetic on XLA's accounting,
    and the ``gather_share`` series uses the memory-bound modeled
    value so hosts without op-level trace support gate identically;
    the JSON keeps the measured share beside it."""
    import tempfile

    from opencompass_tpu import obs
    from opencompass_tpu.models.jax_lm import JaxLM
    from opencompass_tpu.obs import compileaudit
    from opencompass_tpu.obs import timeline as tmod

    work = tempfile.mkdtemp(prefix='oct_devprof_')
    obs.reset_obs()
    os.environ['OCT_PROFILE_STEPS'] = '2'
    os.environ['OCT_PROFILE_STRIDE'] = '4'
    try:
        tracer = obs.init_obs(work)
        tl = obs.init_task_timeline('devprof-bench')
        rng = np.random.RandomState(7)
        prompts = [' '.join(f'w{rng.randint(999)}' for _ in range(int(n)))
                   for n in rng.choice([3, 6, 12, 20], size=8)]
        lm = JaxLM(config='tiny', max_seq_len=256,
                   continuous_batching=True, decode_slots=4,
                   kv_page_size=16)
        lm.get_ppl(prompts[:4])
        lm.generate_continuous(prompts, 12)
        records = list(tmod.iter_records(tl.path))
        summary = tmod.summarize_records(records)
        compiles = compileaudit.read_compiles(tracer.obs_dir)
        audit = compileaudit.summarize_compiles(compiles)
    finally:
        os.environ.pop('OCT_PROFILE_STEPS', None)
        os.environ.pop('OCT_PROFILE_STRIDE', None)
        obs.reset_obs()

    assert audit.get('analyzed', 0) >= 2, (
        f'expected ppl + mixed engine audits, got {audit}')
    assert any(r.get('kind') == 'mixed' for r in compiles), (
        'engine should compile ONE fused mixed executable')
    drift = audit.get('model_drift_max')
    assert drift is not None and drift < 0.25, (
        f'cost model drifted {drift} from XLA accounting '
        f'({audit.get("model_drift_worst_shape")})')
    engines = [r for r in records if r.get('t') == 'engine']
    assert engines, 'engine drain left no flight-recorder record'
    eng = engines[-1]
    gather_modeled = eng.get('gather_share_modeled')
    assert gather_modeled and gather_modeled > 0, (
        'paged engine must report a nonzero modeled gather share')

    record = {
        'v': 1,
        'workload': '8 rows, prompt words in {3..20}, max_new 12, '
                    'tiny JaxLM at max_seq_len 256; ppl scoring + '
                    'engine (4 slots / page 16); 2 sampled step traces',
        'compile_audit': {
            'records': audit.get('records'),
            'fresh': audit.get('fresh'),
            'cache_hits': audit.get('cache_hits'),
            'analyzed': audit.get('analyzed'),
            'compile_seconds': audit.get('compile_seconds'),
            'xla_flops': audit.get('xla_flops'),
            'xla_bytes_accessed': audit.get('xla_bytes_accessed'),
            'temp_bytes_peak': audit.get('temp_bytes_peak'),
        },
        'model_drift': {
            'max': drift,
            'mean': audit.get('model_drift_mean'),
            'worst_shape': audit.get('model_drift_worst_shape'),
            'reconciled': audit.get('reconciled'),
        },
        'shapes': [{'shape_key': r.get('shape_key'),
                    'xla_flops': (r.get('cost') or {}).get('flops'),
                    'model_flops': (r.get('model') or {}).get('flops'),
                    'model_drift': r.get('model_drift')}
                   for r in compiles],
        'step_profile': {
            'profiled_steps': eng.get('profiled_steps'),
            'profile_categories': eng.get('profile_categories'),
            'gather_share': summary.get('gather_share'),
            'gather_share_source': summary.get('gather_share_source'),
            'gather_share_measured': eng.get('gather_share_measured'),
            'gather_share_modeled': gather_modeled,
            'kv_read_path': eng.get('kv_read_path'),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, out_json), 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    _append_trajectory(
        'devprof', 'model_drift', drift, 'frac', direction='lower',
        detail={'worst_shape': audit.get('model_drift_worst_shape'),
                'mean': audit.get('model_drift_mean'),
                'reconciled': audit.get('reconciled')})
    # fresh series name: the modeled share was under-counting KV bytes
    # by num_layers until the ragged-kernel PR's reconciliation fix
    # (kv_token_bytes is per layer; the weight stream spans the depth),
    # so values are not comparable with the old 'gather_share' series
    _append_trajectory(
        'devprof', 'gather_share_modeled', gather_modeled, 'frac',
        direction='lower',
        detail={'source': 'modeled',
                'kv_read_path': eng.get('kv_read_path'),
                'measured': eng.get('gather_share_measured'),
                'profiled_steps': eng.get('profiled_steps')})
    return record


def _bench_obshub(out_json='BENCH_OBSHUB.json'):
    """detail.obshub: the fleet observability hub on a synthetic
    multi-worker fleet — four sources' durable request streams ingested
    into tail-sampled traces and windowed rollups, a p99 answered from
    rollups alone (and cross-checked against the raw nearest-rank
    answer), then the retention budget enforced so the raw streams
    vanish while the query still answers.  Trajectory series gate
    ingest throughput, rollup-query latency, and how much the hub
    shrinks the telemetry footprint."""
    import tempfile

    from opencompass_tpu.obs import hub as hubmod
    from opencompass_tpu.utils.journal import journal_append

    root = tempfile.mkdtemp(prefix='oct_obshub_')
    n_sources, n_records = 4, 1200
    now = time.time()
    t0 = now - 660.0
    rng = np.random.RandomState(11)
    error_ids = []
    for s in range(n_sources):
        src = os.path.join(root, 'worker%d' % s, 'obs')
        os.makedirs(src)
        recs = []
        for i in range(n_records):
            ts = t0 + (i / n_records) * 600.0
            wall = float(0.05 + rng.gamma(2.0, 0.04))
            rid = 'w%d-r%d' % (s, i)
            err = (i % 97 == 13)
            if err:
                error_ids.append(rid)
            recs.append({
                'v': 1, 'id': rid, 'ts': round(ts, 3),
                'route': '/v1/completions', 'model': 'tiny',
                'status': 'error' if err else 'ok',
                'wall_s': round(wall, 5),
                'phases': [
                    {'name': 'prefill', 'start_s': 0.0,
                     'dur_s': round(wall * 0.3, 5)},
                    {'name': 'decode', 'start_s': round(wall * 0.3, 5),
                     'dur_s': round(wall * 0.7, 5)}],
            })
        journal_append(os.path.join(src, 'requests.jsonl'), recs,
                       version=1)
        hubmod.register_source(root, 'host%d' % s, 'worker', src)

    total = n_sources * n_records
    hub = hubmod.ObsHub(root, budget_bytes=1)
    t_start = time.perf_counter()
    stats = hub.ingest(now=now, force_flush=True)
    ingest_s = time.perf_counter() - t_start
    assert stats['ingested'] >= total, (
        'hub ingested %s of %s records' % (stats['ingested'], total))

    raw_ans = hub.query(since=now - 3600.0, q=0.99, raw=True, now=now)
    lat_ms = []
    ans = None
    for _ in range(20):
        q0 = time.perf_counter()
        ans = hub.query(since=now - 3600.0, q=0.99, now=now)
        lat_ms.append((time.perf_counter() - q0) * 1e3)
    query_ms = sorted(lat_ms)[len(lat_ms) // 2]
    assert ans['count'] == total and raw_ans['count'] == total
    rel = abs(ans['value_s'] - raw_ans['value_s']) / raw_ans['value_s']
    assert rel <= 0.05, (
        'rollup p99 %s drifted %.1f%% from raw %s'
        % (ans['value_s'], rel * 100, raw_ans['value_s']))

    kept_errors = {t['trace'] for t in hub.read_traces()
                   if t.get('keep') == 'error'}
    assert set(error_ids) <= kept_errors, (
        'tail sampling dropped %d error traces'
        % len(set(error_ids) - kept_errors))

    comp = hub.compact(now=now)
    after = hubmod.ObsHub(root, budget_bytes=1).query(
        since=now - 3600.0, q=0.99, now=now)
    assert after['count'] == total and comp['raw_bytes_after'] == 0, (
        'post-compaction query lost history: %s' % after)
    footprint_ratio = round(
        comp['raw_bytes_before']
        / max(comp['raw_bytes_after'] + comp['hub_bytes_after'], 1), 2)

    record = {
        'v': 1,
        'workload': '%d sources x %d requests (gamma latencies, ~1%% '
                    'errors), 0.1 sample rate, 1-byte retention budget'
                    % (n_sources, n_records),
        'ingest_records_per_sec': round(total / ingest_s, 1),
        'ingest_wall_s': round(ingest_s, 4),
        'query_p99_ms': round(query_ms, 3),
        'rollup_p99_s': ans['value_s'],
        'raw_p99_s': raw_ans['value_s'],
        'rollup_vs_raw_rel': round(rel, 5),
        'exact_tail': ans.get('exact'),
        'kept_traces': stats['kept'],
        'error_traces_kept': len(kept_errors & set(error_ids)),
        'error_traces_total': len(error_ids),
        'windows_emitted': stats['windows_emitted'],
        'compaction': comp,
        'footprint_ratio': footprint_ratio,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(here, out_json), 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    _append_trajectory(
        'obshub', 'ingest_records_per_sec', record['ingest_records_per_sec'],
        'rec/s', direction='higher',
        detail={'sources': n_sources, 'records': total})
    _append_trajectory(
        'obshub', 'query_ms', record['query_p99_ms'], 'ms',
        direction='lower',
        detail={'exact': ans.get('exact'), 'windows': ans.get('windows')})
    _append_trajectory(
        'obshub', 'footprint_ratio', footprint_ratio, 'x',
        direction='higher',
        detail={'raw_bytes_before': comp['raw_bytes_before'],
                'hub_bytes_after': comp['hub_bytes_after']})
    return record


def _bench_serve(out_json='BENCH_SERVE.json'):
    """detail.serve: the evaluation-as-a-service loop end to end —
    daemon up (fleet warmed), demo sweep enqueued, an interactive
    /v1/completions answered mid-sweep, an identical sweep enqueued
    behind it (served by the store: zero tasks), a repeated completion
    (store hit: zero device rows), then SIGTERM drain.  Records queue
    wait, warm reuse, and interactive latency.  Device-free."""
    import signal
    import subprocess
    import tempfile
    import urllib.request

    here = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix='oct_serve_')
    cfg_path = os.path.join(here, 'configs', 'eval_demo.py')
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               OCT_CACHE_ROOT=os.path.join(tmp, 'cache'))
    env.pop('OCT_TRACE_ID', None)
    env.pop('OCT_OBS_DIR', None)
    log_path = os.path.join(tmp, 'daemon.log')
    log = open(log_path, 'w')
    t_up = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'serve', cfg_path,
         '--port', '0', '--work-dir', os.path.join(tmp, 'out')],
        stdout=log, stderr=subprocess.STDOUT, env=env, cwd=here)

    def http(method, url, body=None, timeout=120):
        req = urllib.request.Request(
            url, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())

    try:
        port = None
        deadline = time.time() + 180
        while time.time() < deadline and port is None:
            if proc.poll() is not None:
                raise RuntimeError('daemon died at startup: '
                                   + open(log_path).read()[-500:])
            for line in open(log_path).read().splitlines():
                if 'engine listening on http://127.0.0.1:' in line:
                    port = int(line.split('127.0.0.1:')[1].split()[0])
            time.sleep(0.2)
        base = f'http://127.0.0.1:{port}'
        while True:
            try:
                code, _ = http('GET', base + '/healthz', timeout=5)
                if code == 200:
                    break
            except Exception:
                pass
            if time.time() > deadline:
                raise RuntimeError('daemon never became ready')
            time.sleep(0.5)
        ready_s = time.perf_counter() - t_up

        t0 = time.perf_counter()
        _, s1 = http('POST', base + '/v1/sweeps',
                     {'config_path': cfg_path, 'mode': 'infer'})
        t1 = time.perf_counter()
        _, comp = http('POST', base + '/v1/completions',
                       {'model': 'fake-demo',
                        'prompt': 'Q: serve bench?\nA:', 'max_tokens': 8})
        interactive_ms = (time.perf_counter() - t1) * 1e3
        mid_sweep = http('GET', f'{base}/v1/sweeps/{s1["id"]}')[1][
            'status'] in ('queued', 'running')
        while http('GET', f'{base}/v1/sweeps/{s1["id"]}')[1][
                'status'] not in ('done', 'failed'):
            time.sleep(0.25)
        cold_wall = time.perf_counter() - t0
        rep1 = http('GET', f'{base}/v1/sweeps/{s1["id"]}')[1]

        # identical sweep behind a warm fleet + full store: the
        # partitioner prunes every task pre-launch
        t0 = time.perf_counter()
        _, s2 = http('POST', base + '/v1/sweeps',
                     {'config_path': cfg_path, 'mode': 'infer'})
        while http('GET', f'{base}/v1/sweeps/{s2["id"]}')[1][
                'status'] not in ('done', 'failed'):
            time.sleep(0.25)
        warm_wall = time.perf_counter() - t0
        rep2 = http('GET', f'{base}/v1/sweeps/{s2["id"]}')[1]

        t1 = time.perf_counter()
        _, comp2 = http('POST', base + '/v1/completions',
                        {'model': 'fake-demo',
                         'prompt': 'Q: serve bench?\nA:',
                         'max_tokens': 8})
        cached_ms = (time.perf_counter() - t1) * 1e3
        # rolling-window serving SLO: a small completion burst (varied
        # prompts — first pass costs device rows, repeats are store
        # hits), then the engine's own /v1/stats summarizes latency
        # percentiles + TTFT over the window
        for i in range(12):
            http('POST', base + '/v1/completions',
                 {'model': 'fake-demo',
                  'prompt': f'Q: slo probe {i % 6}?\nA:',
                  'max_tokens': 8})
        _, stats = http('GET', base + '/v1/stats?window=300')
        slo = (stats.get('completions') or {}).get(
            'per_model', {}).get('fake-demo') or {}
        _, snap = http('GET', base + '/status')
        serve = snap['serve']
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()

    record = {
        'v': 1,
        'workload': 'FakeModel demo sweep through the serve daemon: '
                    'cold sweep + mid-sweep completion, identical warm '
                    'sweep (store-pruned), repeated completion '
                    '(store hit), SIGTERM drain',
        'ready_seconds': round(ready_s, 3),
        'sweep_cold_wall_seconds': round(cold_wall, 3),
        'sweep_warm_wall_seconds': round(warm_wall, 3),
        'sweep_warm_speedup': round(cold_wall / max(warm_wall, 1e-3), 2),
        'queue_wait_seconds': (rep1.get('detail') or {}).get(
            'queue_wait_seconds'),
        'cold_n_tasks': (rep1.get('detail') or {}).get('n_tasks'),
        'warm_n_tasks': (rep2.get('detail') or {}).get('n_tasks'),
        'interactive_mid_sweep': mid_sweep,
        'interactive_latency_ms': round(interactive_ms, 1),
        'interactive_cached_latency_ms': round(cached_ms, 1),
        'interactive_model_built': comp.get('oct', {}).get('model_built'),
        'cached_store_hits': comp2.get('oct', {}).get('store_hits'),
        'cached_device_rows': comp2.get('oct', {}).get('device_rows'),
        # /v1/stats rolling-window SLO over the burst (12 requests):
        # the serving-latency series `ledger check --trajectory` gates
        'completion_count': slo.get('count'),
        'completion_p50_ms': slo.get('p50_ms'),
        'completion_p99_ms': slo.get('p99_ms'),
        # TTFT estimate (device rows only); null on the FakeModel
        # bench, populated on real JaxLM-served fleets
        'ttft_p95_ms': slo.get('ttft_p95_ms'),
        'worker_spawns': serve.get('worker_spawns'),
        'worker_reuses': serve.get('worker_reuses'),
        'drain_exit_code': proc.returncode,
    }
    try:
        with open(os.path.join(here, out_json), 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    _append_trajectory(
        'serve', 'interactive_cached_latency_ms',
        record['interactive_cached_latency_ms'], 'ms', direction='lower',
        detail={'warm_n_tasks': record['warm_n_tasks'],
                'worker_reuses': record['worker_reuses'],
                'queue_wait_seconds': record['queue_wait_seconds']})
    if record.get('completion_p99_ms') is not None:
        _append_trajectory(
            'serve', 'completion_p99_ms', record['completion_p99_ms'],
            'ms', direction='lower',
            detail={'completion_p50_ms': record['completion_p50_ms'],
                    'ttft_p95_ms': record['ttft_p95_ms'],
                    'completion_count': record['completion_count']})
    return record


def _bench_slo(out_json='BENCH_SERVE.json'):
    """detail.slo: the burn-rate alerting loop end to end — a serve
    daemon with a tight latency objective, the 12-request burst
    replayed with an injected per-completion sleep past the objective
    (file-based knob, so the slowdown can be LIFTED mid-daemon),
    asserting the alert fires (alerts.jsonl + /v1/alerts + /metrics +
    /healthz degraded) and then resolves once the fast window
    recovers.  Also records the measured inter-token-latency
    percentiles (`itl_p99_ms`) the engine path now reports.
    Device-free (continuous FakeModel with paced token emission)."""
    import signal
    import subprocess
    import tempfile
    import urllib.request

    here = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix='oct_slo_')
    objective_ms = 200.0
    sleep_file = os.path.join(tmp, 'sleep_s')
    with open(sleep_file, 'w') as f:
        f.write('0.5')
    cfg_path = os.path.join(tmp, 'serve_slo.py')
    with open(cfg_path, 'w') as f:
        f.write(f"""
from opencompass_tpu.models import FakeModel
models = [dict(type=FakeModel, abbr='fake-slo', path='fake',
               continuous=True,
               canned_responses={{'Q': 'tok ' * 8}},
               run_cfg=dict(num_devices=0))]
slos = [dict(name='completion_latency', kind='latency',
             objective_ms={objective_ms}, target=0.5,
             fast_s=5.0, slow_s=30.0, burn_factor=1.5,
             min_samples=3, severity='page')]
slo_eval_interval_s = 0.5
work_dir = {os.path.join(tmp, 'out')!r}
""")
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               OCT_CACHE_ROOT=os.path.join(tmp, 'cache'),
               OCT_DEBUG_COMPLETE_SLEEP_FILE=sleep_file,
               OCT_FAKE_TOKEN_SLEEP_S='0.003')
    env.pop('OCT_TRACE_ID', None)
    env.pop('OCT_OBS_DIR', None)
    log_path = os.path.join(tmp, 'daemon.log')
    log = open(log_path, 'w')
    proc = subprocess.Popen(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'serve', cfg_path,
         '--port', '0'],
        stdout=log, stderr=subprocess.STDOUT, env=env, cwd=here)

    def http(method, url, body=None, timeout=60):
        req = urllib.request.Request(
            url, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={'Content-Type': 'application/json'})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())

    fired_after_s = resolved_after_s = None
    degraded_during_burn = None
    try:
        port = None
        deadline = time.time() + 180
        while time.time() < deadline and port is None:
            if proc.poll() is not None:
                raise RuntimeError('daemon died at startup: '
                                   + open(log_path).read()[-800:])
            for line in open(log_path).read().splitlines():
                if 'engine listening on http://127.0.0.1:' in line:
                    port = int(line.split('127.0.0.1:')[1].split()[0])
            time.sleep(0.2)
        base = f'http://127.0.0.1:{port}'
        while True:
            try:
                code, _ = http('GET', base + '/healthz', timeout=5)
                if code == 200:
                    break
            except Exception:
                pass
            if time.time() > deadline:
                raise RuntimeError('daemon never became ready')
            time.sleep(0.5)

        def active_rules():
            _, alerts = http('GET', base + '/v1/alerts')
            return [a['rule'] for a in alerts.get('active') or []]

        # burst A: the 12-request serve burst, each one slowed past
        # the objective by the injected sleep (unique prompts — store
        # hits would dodge the device path, not the sleep, but keep
        # the replay honest)
        t_burn = time.perf_counter()
        for i in range(12):
            http('POST', base + '/v1/completions',
                 {'model': 'fake-slo',
                  'prompt': f'Q: slo burn probe {i}?\nA:',
                  'max_tokens': 8})
            if fired_after_s is None \
                    and 'completion_latency' in active_rules():
                fired_after_s = time.perf_counter() - t_burn
        while fired_after_s is None \
                and time.perf_counter() - t_burn < 20:
            if 'completion_latency' in active_rules():
                fired_after_s = time.perf_counter() - t_burn
                break
            time.sleep(0.25)
        if fired_after_s is None:
            raise RuntimeError('burn-rate alert never fired')
        _, health = http('GET', base + '/healthz')
        degraded_during_burn = health.get('degraded')

        # lift the slowdown; fresh fast requests push the slow samples
        # out of the fast window and the alert resolves
        with open(sleep_file, 'w') as f:
            f.write('0')
        t_lift = time.perf_counter()
        i = 0
        while time.perf_counter() - t_lift < 30:
            http('POST', base + '/v1/completions',
                 {'model': 'fake-slo',
                  'prompt': f'Q: slo recovery probe {i}?\nA:',
                  'max_tokens': 8})
            i += 1
            if 'completion_latency' not in active_rules():
                resolved_after_s = time.perf_counter() - t_lift
                break
            time.sleep(0.5)
        if resolved_after_s is None:
            raise RuntimeError('burn-rate alert never resolved after '
                               'the slowdown lifted')

        _, stats = http('GET', base + '/v1/stats?window=300')
        slo_row = (stats.get('completions') or {}).get(
            'per_model', {}).get('fake-slo') or {}
        _, alerts = http('GET', base + '/v1/alerts')
        import urllib.request as _ur
        with _ur.urlopen(base + '/metrics', timeout=10) as r:
            metrics_text = r.read().decode()
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()

    alerts_file = os.path.join(tmp, 'cache', 'serve', 'obs',
                               'alerts.jsonl')
    transitions = [json.loads(line) for line
                   in open(alerts_file, encoding='utf-8')
                   if line.strip()]
    kinds = [t['t'] for t in transitions
             if t.get('rule') == 'completion_latency']
    assert 'fire' in kinds and 'resolve' in kinds, kinds
    assert 'oct_alert_active' in metrics_text
    assert 'oct_slo_budget_remaining' in metrics_text
    # dead-daemon alert pane renders from the alerts.jsonl tail
    top = subprocess.run(
        [sys.executable, '-m', 'opencompass_tpu.cli', 'top',
         os.path.join(tmp, 'cache'), '--once'],
        env=env, cwd=here, capture_output=True, text=True, timeout=120)

    slo_record = {
        'workload': '12-request serve burst with a 0.5s injected '
                    'per-completion sleep past a 200ms p50 latency '
                    'objective (fast 5s / slow 30s windows, burn '
                    'factor 1.5), then lifted',
        'objective_ms': objective_ms,
        'injected_sleep_s': 0.5,
        'alert_fired': True,
        'fire_latency_s': round(fired_after_s, 2),
        'alert_resolved': True,
        'resolve_latency_s': round(resolved_after_s, 2),
        'healthz_degraded_during_burn': degraded_during_burn,
        'alert_transitions': len(transitions),
        'recent_transitions': len(alerts.get('recent') or []),
        # measured engine-path serving latencies over the whole window
        'completion_count': slo_row.get('count'),
        'completion_p99_ms': slo_row.get('p99_ms'),
        'ttft_p95_ms': slo_row.get('ttft_p95_ms'),
        'itl_p50_ms': slo_row.get('itl_p50_ms'),
        'itl_p99_ms': slo_row.get('itl_p99_ms'),
        'top_file_mode_alert_pane': 'alerts:' in top.stdout,
    }
    # merge into BENCH_SERVE.json next to the --serve leg's record
    path = os.path.join(here, out_json)
    try:
        with open(path, encoding='utf-8') as f:
            record = json.load(f)
        if not isinstance(record, dict):
            record = {}
    except (OSError, ValueError):
        record = {}
    record['slo'] = slo_record
    record['itl_p99_ms'] = slo_record['itl_p99_ms']
    try:
        with open(path, 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    if slo_record.get('itl_p99_ms') is not None:
        _append_trajectory(
            'serve', 'itl_p99_ms', slo_record['itl_p99_ms'], 'ms',
            direction='lower',
            detail={'itl_p50_ms': slo_record['itl_p50_ms'],
                    'ttft_p95_ms': slo_record['ttft_p95_ms'],
                    'fire_latency_s': slo_record['fire_latency_s'],
                    'resolve_latency_s':
                        slo_record['resolve_latency_s']})
    return slo_record


def _bench_chaos(out_json='BENCH_CHAOS.json'):
    """detail.chaos: the full serve-layer chaos sweep (analysis/
    chaos.py) against a live daemon — overload burst past the
    admission ceiling (429 + measured Retry-After, admitted p99 within
    the objective), stuck worker vs propagated deadlines (504 with the
    phase that ate the budget), worker SIGKILL mid-request (retry
    budget + circuit breaker open → half-open probe → close), and
    store write EIO (cache-off degradation, bit-identical
    convergence).  Any violated invariant raises; the record landing
    in BENCH_CHAOS.json IS the all-clear.  Device-free (continuous
    FakeModel)."""
    import tempfile

    from opencompass_tpu.analysis import chaos

    here = os.path.dirname(os.path.abspath(__file__))
    workdir = tempfile.mkdtemp(prefix='oct_chaos_')
    report = chaos.run_chaos(workdir=workdir, quick=False)
    scen = report['scenarios']
    record = {
        'workload': 'full chaos sweep vs one live daemon: '
                    f'{", ".join(scen)} — every degradation '
                    'invariant asserted (violations raise; this '
                    'record is the all-clear)',
        'scenarios_passed': len(scen),
        'requests_checked': report['requests_checked'],
        'wall_s': report['wall_s'],
        'overload': scen.get('overload_burst'),
        'stuck_worker': scen.get('stuck_worker'),
        'worker_kill': scen.get('worker_kill'),
        'store_eio': scen.get('store_eio'),
    }
    path = os.path.join(here, out_json)
    try:
        with open(path, 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    # the degradation gate rides the trajectory: scenario count must
    # not shrink, and admitted-traffic p99 under overload is the
    # number admission control exists to protect
    _append_trajectory(
        'chaos', 'scenarios_passed', record['scenarios_passed'],
        'count', direction='higher',
        detail={'requests_checked': record['requests_checked']})
    p99 = (scen.get('overload_burst') or {}).get('admitted_p99_ms')
    if p99 is not None:
        _append_trajectory(
            'chaos', 'overload_admitted_p99_ms', p99, 'ms',
            direction='lower',
            detail={'objective_ms': chaos.OBJECTIVE_MS,
                    'shed': (scen.get('overload_burst') or {})
                    .get('shed'),
                    'admitted': (scen.get('overload_burst') or {})
                    .get('admitted')})
    return record


def _bench_loadgen(out_json='BENCH_LOADGEN.json'):
    """detail.loadgen: the replay load generator (opencompass_tpu/
    loadgen) against a live autoscaler-enabled daemon, every request
    streamed over SSE so TTFT is a measured first-byte delivery
    timestamp and ITL comes from inter-frame gaps at the client.

    Two legs: (1) a recorded trace replayed at 20x its native pacing
    (``arrival='replay'``) — the measured compression factor is the
    replay-speedup number and must clear 10x; (2) a sustained open-loop
    Poisson step that the autoscaler has to absorb — the gap between
    step start and the first journaled scale-up decision is the
    elasticity latency.  Device-free (continuous FakeModel)."""
    import os.path as osp
    import shutil
    import tempfile

    from opencompass_tpu.analysis.chaos import ChaosDaemon, _jsonl
    from opencompass_tpu.loadgen.replay import run_load, synth_trace

    here = os.path.dirname(os.path.abspath(__file__))
    workdir = tempfile.mkdtemp(prefix='oct_loadgen_')
    extra = (
        'autoscaler = dict(min_replicas=1, max_replicas=3,\n'
        '                  interval_s=0.25, scale_up_cooldown_s=1.0,\n'
        '                  scale_down_cooldown_s=2.0,\n'
        '                  up_queue_eta_s=5.0, up_slot_util=0.2,\n'
        '                  down_slot_util=0.5, up_consecutive=2,\n'
        '                  down_consecutive=6)\n')
    daemon = ChaosDaemon(workdir, max_inflight=8, extra_cfg=extra)
    try:
        daemon.start()
        host = '127.0.0.1'
        port = int(daemon.base.rsplit(':', 1)[1])

        # -- leg 1: recorded-timestamp replay, 20x compression.  The
        # trace's native span is (n-1)/rate seconds; light service time
        # so the measured wall is arrival-schedule-bound, not
        # seat-bound.
        daemon.set_sleep(0.01)
        n_replay, native_rate, compress = 40, 0.5, 20.0
        trace = synth_trace(n_replay, 'fake-chaos', rate=native_rate,
                            max_tokens=8, prefix='Q: replay row')
        native_span_s = (trace[-1]['ts'] - trace[0]['ts'])
        replay = run_load(host, port, trace, stream=True,
                          arrival='replay', speedup=compress, seed=3)
        replay_speedup = native_span_s / max(replay['wall_s'], 1e-9)

        # -- leg 2: sustained 10x Poisson step; scale-up latency is
        # first journaled 'up' ts minus step start (both wall-clock)
        daemon.set_sleep(0.2)
        t_step = time.time()
        stepped = run_load(
            host, port,
            synth_trace(60, 'fake-chaos', rate=1.5, max_tokens=8,
                        prefix='Q: bench step row'),
            stream=True, arrival='poisson', speedup=10.0, seed=11)
        daemon.set_sleep(0)
        ups = [r for r in _jsonl(osp.join(daemon.serve_obs_dir,
                                          'autoscaler.jsonl'))
               if r.get('direction') == 'up' and r.get('ts')]
        scale_up_latency_s = (min(r['ts'] for r in ups) - t_step) \
            if ups else None

        record = {
            'workload': f'replay {n_replay} recorded rows at '
                        f'{compress:.0f}x native pacing + 60-row '
                        '10x Poisson step vs one autoscaler-enabled '
                        'daemon (FakeModel, SSE streaming on every '
                        'request)',
            'replay': {
                'native_span_s': round(native_span_s, 2),
                'wall_s': replay['wall_s'],
                'speedup': round(replay_speedup, 2),
                'completed': replay['completed'],
                'errors': replay['errors'],
            },
            'sustained_rps': stepped['sustained_rps'],
            'offered_rps': stepped['offered_rps'],
            'ttft_p95_ms': stepped['ttft_ms']['p95'],
            'itl_p99_ms': stepped['itl_ms']['p99'],
            'frames_total': stepped['frames_total'],
            'scale_ups': len(ups),
            'scale_up_latency_s': (round(scale_up_latency_s, 3)
                                   if scale_up_latency_s is not None
                                   else None),
            'shed': stepped['status_counts'].get('429', 0),
        }
    finally:
        daemon.stop()
        shutil.rmtree(workdir, ignore_errors=True)
    path = os.path.join(here, out_json)
    try:
        with open(path, 'w') as f:
            json.dump(record, f, indent=2)
    except OSError:
        pass
    if record['ttft_p95_ms'] is not None:
        _append_trajectory(
            'loadgen', 'ttft_p95_ms', record['ttft_p95_ms'], 'ms',
            direction='lower',
            detail={'itl_p99_ms': record['itl_p99_ms'],
                    'frames_total': record['frames_total'],
                    'scale_up_latency_s': record['scale_up_latency_s']})
    _append_trajectory(
        'loadgen', 'sustained_rps', record['sustained_rps'], 'rps',
        direction='higher',
        detail={'offered_rps': record['offered_rps'],
                'replay_speedup': record['replay']['speedup'],
                'shed': record['shed']})
    return record


def _bench_outbound(out_json='BENCH_OUTBOUND.json'):
    """detail.outbound: API-sweep wall-clock through the outbound
    scheduler (AIMD in-flight window + Retry-After pacing + budgeted
    jittered retries) vs the serial arrival-order baseline — the
    pre-scheduler path, one row at a time through the retrying
    ``post_json`` — against the local fault-injecting stub provider at
    150 ms injected latency with a 20% 429 mix (Retry-After 0.25 s).
    Both paths must produce identical outputs (the stub is a
    deterministic function of the prompt) and the scheduler must beat
    serial by >= 3x; violations raise, the record is the all-clear.
    Device-free."""
    from opencompass_tpu.models.openai_api import OpenAI
    from opencompass_tpu.outbound import StubProvider, canned_text

    N = 40
    LATENCY_S = 0.15
    MIX_EVERY = 5            # every 5th request answers 429 — 20% mix
    RETRY_AFTER_S = 0.25
    provider = StubProvider(latency_s=LATENCY_S).start()
    try:
        provider.set_429_every(MIX_EVERY, retry_after_s=RETRY_AFTER_S)
        prompts = [f'bench outbound row {i}' for i in range(N)]
        expected = [canned_text(p) for p in prompts]

        # serial arrival-order baseline: every row waits for the
        # previous one, 429 sleeps happen inline (qps cap effectively
        # open so only scheduling is measured, not the config knob)
        serial_model = OpenAI(path='bench-serial', key='k',
                              openai_api_base=provider.chat_url,
                              query_per_second=100000, retry=3)
        t0 = time.perf_counter()
        serial_out = []
        for p in prompts:
            body = {'model': 'bench-serial', 'max_tokens': 8,
                    'messages': [{'role': 'user', 'content': p}]}
            data = serial_model.post_json(provider.chat_url, body)
            serial_out.append(
                data['choices'][0]['message']['content'].strip())
        serial_wall = time.perf_counter() - t0
        assert serial_out == expected, 'serial baseline diverged'
        serial_stats = provider.stats()

        provider.reset_stats()
        sched_model = OpenAI(path='bench-outbound', key='k',
                             openai_api_base=provider.chat_url,
                             query_per_second=100000, retry=3,
                             max_inflight=8,
                             outbound=dict(retry_budget_rate=10.0,
                                           retry_budget_burst=24.0))
        t0 = time.perf_counter()
        out = sched_model.generate(prompts, max_out_len=8)
        outbound_wall = time.perf_counter() - t0
        assert out == expected, 'outbound sweep diverged'
        outbound_stats = provider.stats()
        sched_stats = sched_model.outbound_scheduler().stats()
    finally:
        provider.stop()
    speedup = serial_wall / outbound_wall
    assert speedup >= 3.0, (
        f'outbound sweep only {speedup:.2f}x over serial '
        f'({outbound_wall:.2f}s vs {serial_wall:.2f}s) — below the '
        '3x acceptance bar')
    record = {
        'workload': f'{N} rows vs the stub provider at '
                    f'{LATENCY_S * 1e3:.0f}ms injected latency, '
                    f'1-in-{MIX_EVERY} 429 mix '
                    f'(Retry-After {RETRY_AFTER_S}s); identical '
                    'outputs asserted both paths',
        'serial_wall_s': round(serial_wall, 3),
        'outbound_wall_s': round(outbound_wall, 3),
        'speedup': round(speedup, 2),
        'serial_requests': serial_stats['requests_total'],
        'serial_429s': serial_stats['http_429'],
        'outbound_requests': outbound_stats['requests_total'],
        'outbound_429s': outbound_stats['http_429'],
        'outbound_max_concurrent': outbound_stats['max_concurrent'],
        'scheduler': {
            'retries': sched_stats['retries_total'],
            'budget_refusals': sched_stats['retry_budget_refusals'],
            'limit_final': sched_stats['limiter']['limit'],
            'limit_low_water': sched_stats['limiter']['low_water'],
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        from opencompass_tpu.utils.fileio import atomic_write_json
        atomic_write_json(os.path.join(here, out_json), record,
                          dump_kwargs={'indent': 2})
    except OSError:
        pass
    # the trajectory gate rides the scheduler's wall clock: the sweep
    # must stay fast under the same injected throttle workload
    _append_trajectory(
        'outbound', 'wall_s', record['outbound_wall_s'], 's',
        direction='lower',
        detail={'speedup': record['speedup'],
                'serial_wall_s': record['serial_wall_s'],
                'max_concurrent': record['outbound_max_concurrent']})
    return record


def main():
    n_chips = max(1, len(jax.devices()))
    kind = getattr(jax.devices()[0], 'device_kind', '')
    peak = _PEAK_TFLOPS.get(kind)

    # continuity config first (small; freed before the 7B params land);
    # batch 32 matches BENCH_r01's 'PPL b32xs512' so values are comparable
    params = init_params(CFG_SMALL, jax.random.PRNGKey(0))
    small_ppl, _ = _bench_ppl(params, CFG_SMALL, 8, batch=32)
    small_gen, small_tps = _bench_gen(params, CFG_SMALL)
    small_value = _blend(small_ppl, small_gen) / n_chips
    del params

    params = jax.jit(init_params, static_argnums=0)(
        CFG_7B, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    ppl_sps, ppl_tflops = _bench_ppl(params, CFG_7B, PPL_ITERS)
    _, ppl_tflops_noflash = _bench_ppl(params, CFG_7B, PPL_ITERS,
                                       use_flash=False)
    # long-context scoring leg: 4x the headline sequence through the
    # flash kernel (the reference truncates instead; SURVEY §5
    # long-context row)
    long_sps, long_tflops = _bench_ppl(params, CFG_7B, LONG_ITERS,
                                       batch=LONG_BATCH, seq=LONG_SEQ)
    gen_sps, gen_tps = _bench_gen(params, CFG_7B)
    jax.clear_caches()  # drop timed executables' program space first
    # headline-accuracy leg (VERDICT r03 #1): the quantized configs the
    # headline rides are scored for agreement against THIS bf16 model at
    # full 7B geometry — scoring pool now, quantized halves below.
    # Pool sizes chosen to fit next to the 13.5 GB weights on a 16 GB
    # chip (see nn/agreement.py docstrings).
    AG_ITEMS, AG_CHOICES = 32, 4
    ag_tok, ag_mask, ag_prompts, ag_pmask = eval_pool(
        CFG_7B, AG_ITEMS, AG_CHOICES, seq=128, gen_batch=16,
        gen_prompt=GEN_PROMPT)
    ag_nll_fp = score_pool(params, CFG_7B, ag_tok, ag_mask)
    ag_forced = jax.jit(lambda p, t, m: greedy_generate(
        p, CFG_7B, t, m, GEN_NEW, eos_token_id=None)[0])(
            params, ag_prompts, ag_pmask)
    ag_forced = jnp.asarray(np.asarray(ag_forced))
    ag_lp_fp, ag_am_fp, ag_margin_fp, _ = forced_decode(
        params, CFG_7B, ag_prompts, ag_pmask, ag_forced)
    del params
    jax.clear_caches()

    # int8 weight-only decode (nn/quant.py): the gen path is weight-read
    # bound, so halving weight bytes is the first decode lever.  One
    # fused init+quantize program keeps peak HBM at the bf16 model size.
    from opencompass_tpu.nn.quant import quantize_params
    qparams = jax.jit(
        lambda key: quantize_params(init_params(CFG_7B, key), CFG_7B))(
            jax.random.PRNGKey(0))
    jax.block_until_ready(qparams)
    jax.clear_caches()
    # W8A8 scoring: int8 x int8 on the MXU runs the prefill/scoring
    # matmuls ~1.5x the bf16 rate — the headline PPL leg
    cfg_aq = dataclasses.replace(CFG_7B, act_quant=True)
    ppl8_sps, ppl8_tops = _bench_ppl(qparams, cfg_aq, PPL_ITERS)
    jax.clear_caches()
    gen8_sps, gen8_tps = _bench_gen(qparams, CFG_7B)
    jax.clear_caches()
    # int8 KV cache on top (per-vector scales; decode-only).  NOTE:
    # from r5 every int8-KV decode rides the Pallas kernel — these b32/
    # b64 rows are NOT path-comparable with the r4 XLA-attention rows
    cfg_kv = dataclasses.replace(CFG_7B, kv_quant='int8')
    gen8kv_sps, gen8kv_tps = _bench_gen(qparams, cfg_kv)
    jax.clear_caches()
    gen8kv64_sps, gen8kv64_tps = _bench_gen(qparams, cfg_kv, batch=64)
    jax.clear_caches()
    # int4 KV at batch 128 (XLA path; r4 headline — kept for
    # continuity and as the long-context capacity point)
    cfg_kv4 = dataclasses.replace(CFG_7B, kv_quant='int4', act_quant=True)
    gen4kv_sps, gen4kv_tps = _bench_gen(qparams, cfg_kv4,
                                        batch=GEN_BATCH_HEADLINE)
    jax.clear_caches()
    # headline gen: W8A8 matmuls + int8 KV through the Pallas
    # decode-attention kernel — per-step attention drops from ~21 ms
    # (XLA whole-cache bf16 materialization) to ~6 ms at batch 128
    cfg_hl = dataclasses.replace(CFG_7B, kv_quant='int8', act_quant=True)
    genhl_sps, genhl_tps = _bench_gen(qparams, cfg_hl,
                                      batch=GEN_BATCH_HEADLINE)
    jax.clear_caches()
    # long-context generation leg: p1024 prompts at the largest batch
    # whose int8 cache fits beside the weights (the reference truncates
    # long inputs instead; SURVEY long-context row).  Exercises the
    # decode kernel's multi-chunk online softmax on-chip.
    glong_sps, glong_tps = _bench_gen(qparams, cfg_hl,
                                      batch=GEN_LONG_BATCH,
                                      prompt=GEN_LONG_PROMPT)
    jax.clear_caches()
    # quantized halves of the headline-accuracy leg (same pool, same
    # weights re-materialized as int8 from the same PRNG key)
    ag_nll_q = score_pool(qparams, cfg_aq, ag_tok, ag_mask)
    ag_lp_q, ag_am_q, _, ag_rank_q = forced_decode(
        qparams, cfg_hl, ag_prompts, ag_pmask, ag_forced)
    jax.clear_caches()

    # shared-prefix eval-workload leg (nn/loss.shared_prefix_nll for
    # scoring, nn/decode.greedy_generate_prefixed for generation):
    # 5-shot-shaped prompts — a 1408-token common ICE block + 128-token
    # per-item remainders — scored/generated with the prefix prefilled
    # once vs the plain full-prompt paths.  This is the pipeline's
    # actual hot shape on MMLU-class few-shot tasks (BASELINE_RUN.md).
    from opencompass_tpu.nn import (greedy_generate_prefixed,
                                    shared_prefix_nll)
    SP_P, SP_S, SP_B, SP_NEW = 1408, 128, 8, 100
    rsp = np.random.RandomState(9)
    sp_pre = jnp.asarray(rsp.randint(0, 32000, (SP_P,)), jnp.int32)
    sp_rows = jnp.asarray(rsp.randint(0, 32000, (SP_B, SP_S)), jnp.int32)
    sp_mask = jnp.ones((SP_B, SP_S), jnp.bool_)
    sp_full = jnp.concatenate(
        [jnp.broadcast_to(sp_pre, (SP_B, SP_P)), sp_rows], axis=1)
    sp_fmask = jnp.ones_like(sp_full, jnp.bool_)

    def timeit(fn, *args, iters=4):
        np.asarray(fn(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        np.asarray(out)
        return SP_B / ((time.perf_counter() - t0) / iters)

    ppl_plain = timeit(jax.jit(lambda p, t, m: sequence_nll(
        forward(p, cfg_aq, t, m), t, m)), qparams, sp_full, sp_fmask)
    ppl_shared = timeit(jax.jit(lambda p, pre, t, m: shared_prefix_nll(
        p, cfg_aq, pre, t, m)), qparams, sp_pre, sp_rows, sp_mask)
    jax.clear_caches()
    gen_plain = timeit(jax.jit(lambda p, t, m: greedy_generate(
        p, cfg_hl, t, m, SP_NEW, eos_token_id=None)[0]),
        qparams, sp_full, sp_fmask, iters=1)
    gen_shared = timeit(jax.jit(
        lambda p, pre, t, m: greedy_generate_prefixed(
            p, cfg_hl, pre, t, m, SP_NEW, eos_token_id=None)[0]),
        qparams, sp_pre, sp_rows, sp_mask, iters=1)
    shared_leg = {
        'workload': '5-shot shape: prefix %d + suffix %d, batch %d, '
                    'W8A8(+int8-KV gen)' % (SP_P, SP_S, SP_B),
        'ppl_plain_samples_per_sec': round(ppl_plain, 3),
        'ppl_shared_samples_per_sec': round(ppl_shared, 3),
        'ppl_speedup': round(ppl_shared / ppl_plain, 2),
        'gen_plain_samples_per_sec': round(gen_plain, 3),
        'gen_shared_samples_per_sec': round(gen_shared, 3),
        'gen_speedup': round(gen_shared / gen_plain, 2),
    }
    agreement = {
        'scoring_w8a8_vs_bf16': scoring_stats(ag_nll_fp, ag_nll_q,
                                              AG_CHOICES),
        'forced_decode_w8a8kv8_vs_bf16': forced_stats(
            ag_forced, ag_am_fp, ag_margin_fp, ag_lp_fp, ag_am_q,
            ag_rank_q, ag_lp_q),
        'pool': {'items': AG_ITEMS, 'choices': AG_CHOICES, 'seq': 128,
                 'gen_rows': 16, 'gen_prompt': GEN_PROMPT,
                 'gen_new': GEN_NEW},
    }
    del qparams
    jax.clear_caches()

    # int4x2 packed weights (nn/quant.py): two group-quantized int4 per
    # uint8, nibbles split inside the matmul program.  NOT a throughput
    # tier on this toolchain — XLA materializes the unpacked operand
    # instead of fusing it into the matmul read, so w4a8 decode measures
    # SLOWER than w8a8 (docs/user_guides/performance.md roofline) — but
    # it is the CAPACITY tier: weights at rest are 4-bit, which is what
    # lets 13B-class geometry decode on one 16 GB chip below.
    q4 = jax.jit(
        lambda key: quantize_params(init_params(CFG_7B, key), CFG_7B,
                                    mode='int4x2'))(jax.random.PRNGKey(0))
    jax.block_until_ready(q4)
    jax.clear_caches()
    gen4_sps, gen4_tps = _bench_gen(q4, cfg_kv4, batch=GEN_BATCH_HEADLINE)
    jax.clear_caches()
    # w4 + int8 KV rides BOTH kernels (stacked-weight matmuls keep the
    # HBM weight stream 4-bit; decode attention reads int8 tiles) —
    # measured 1.6x over the XLA packed route at this batch
    gen4k8_sps, gen4k8_tps = _bench_gen(q4, cfg_hl,
                                        batch=GEN_BATCH_HEADLINE)
    jax.clear_caches()
    ppl4_sps, ppl4_tops = _bench_ppl(q4, cfg_aq, PPL_ITERS)
    del q4
    jax.clear_caches()

    # capacity leg: llama-13B geometry on ONE 16 GB chip.  bf16 (26 GB)
    # and int8 (13 GB + cache) cannot run at all; the packed form can —
    # weights 6.5 GB at rest.  Random packed init (nn/quant.py
    # init_packed_params): the bf16 stack a fused init+quantize would
    # need exceeds HBM by construction here.
    from opencompass_tpu.nn.quant import init_packed_params
    CFG_13B = TransformerConfig.llama(
        vocab_size=32000, hidden_size=5120, num_layers=40, num_heads=40,
        num_kv_heads=40, intermediate_size=13824, max_seq_len=2048)
    cfg13_hl = dataclasses.replace(CFG_13B, kv_quant='int4',
                                   act_quant=True)
    cfg13_aq = dataclasses.replace(CFG_13B, act_quant=True)
    q13 = jax.jit(lambda key: init_packed_params(CFG_13B, key))(
        jax.random.PRNGKey(0))
    jax.block_until_ready(q13)
    jax.clear_caches()
    gen13_sps, gen13_tps = _bench_gen(q13, cfg13_hl, batch=32)
    jax.clear_caches()
    # kernel-path variant: int8 KV (decode-attention kernel) + stacked
    # 4-bit weight matmuls; kv4 above remains the long-context capacity
    # point (an int8 cache at s2048 would not fit beside the weights)
    cfg13_k8 = dataclasses.replace(CFG_13B, kv_quant='int8',
                                   act_quant=True)
    gen13k8_sps, gen13k8_tps = _bench_gen(q13, cfg13_k8, batch=32)
    jax.clear_caches()
    ppl13_sps, _ = _bench_ppl(q13, cfg13_aq, 4, batch=8)
    del q13
    jax.clear_caches()

    # headline: the serving/throughput config end to end — W8A8 scoring +
    # W8A8/int8-KV batch-128 generation through the Pallas decode kernel
    # (accuracy tracked vs bf16 by tests/test_quant.py and the agreement
    # leg above); value_bf16 is the same blend fully unquantized
    value = _blend(ppl8_sps, genhl_sps) / n_chips
    # baseline granted the headline's batch (like for like); the b32
    # estimate of BENCH_r01/r02 is kept in detail for continuity
    a100 = _a100_estimate(CFG_7B, gen_batch=GEN_BATCH_HEADLINE)
    a100_b32 = _a100_estimate(CFG_7B, gen_batch=GEN_BATCH)
    record = {
        'metric': 'eval samples/sec/chip (PPL b%dxs%d W8A8 + gen b%d '
                  'p%d+%d W8A8/int8-KV, llama-7B)' % (
                      PPL_BATCH, PPL_SEQ, GEN_BATCH_HEADLINE, GEN_PROMPT,
                      GEN_NEW),
        'value': round(value, 3),
        'unit': 'samples/sec/chip',
        'vs_baseline': round(value / a100['blended'], 3),
        'detail': {
            'ppl_samples_per_sec': round(ppl8_sps, 3),
            'ppl_tops': round(ppl8_tops, 1),
            'ppl_quantize': 'W8A8 (int8 weights per-out-channel + dynamic '
                            'per-token int8 activations, int8 MXU)',
            'ppl_bf16_samples_per_sec': round(ppl_sps, 3),
            'ppl_tflops': round(ppl_tflops, 1),
            'ppl_mfu': round(ppl_tflops / peak, 3) if peak else None,
            'ppl_tflops_noflash': round(ppl_tflops_noflash, 1),
            'flash_speedup': round(ppl_tflops / ppl_tflops_noflash, 3),
            'ppl_long_s%d_samples_per_sec' % LONG_SEQ:
                round(long_sps, 3),
            'ppl_long_s%d_tflops' % LONG_SEQ: round(long_tflops, 1),
            'gen_long_p%d_b%d_samples_per_sec' % (
                GEN_LONG_PROMPT, GEN_LONG_BATCH): round(glong_sps, 3),
            'gen_long_p%d_b%d_tokens_per_sec' % (
                GEN_LONG_PROMPT, GEN_LONG_BATCH): round(glong_tps, 1),
            'gen_samples_per_sec': round(genhl_sps, 3),
            'gen_tokens_per_sec': round(genhl_tps, 1),
            'gen_quantize': 'W8A8 matmuls + int8 KV cache (per-vector '
                            'scales) via the Pallas decode-attention '
                            'kernel, batch %d' % GEN_BATCH_HEADLINE,
            'gen_w8a8kv4_b%d_samples_per_sec' % GEN_BATCH_HEADLINE:
                round(gen4kv_sps, 3),
            'gen_w8a8kv4_b%d_tokens_per_sec' % GEN_BATCH_HEADLINE:
                round(gen4kv_tps, 1),
            'gen_bf16_samples_per_sec': round(gen_sps, 3),
            'gen_bf16_tokens_per_sec': round(gen_tps, 1),
            'gen_int8_b32_samples_per_sec': round(gen8_sps, 3),
            'gen_int8_b32_tokens_per_sec': round(gen8_tps, 1),
            'gen_int8kv_samples_per_sec': round(gen8kv_sps, 3),
            'gen_int8kv_tokens_per_sec': round(gen8kv_tps, 1),
            'gen_int8kv_b64_samples_per_sec': round(gen8kv64_sps, 3),
            'gen_int8kv_b64_tokens_per_sec': round(gen8kv64_tps, 1),
            'gen_w4a8kv4_b%d_samples_per_sec' % GEN_BATCH_HEADLINE:
                round(gen4_sps, 3),
            'gen_w4a8kv4_b%d_tokens_per_sec' % GEN_BATCH_HEADLINE:
                round(gen4_tps, 1),
            'gen_w4a8kv8_b%d_samples_per_sec' % GEN_BATCH_HEADLINE:
                round(gen4k8_sps, 3),
            'gen_w4a8kv8_b%d_tokens_per_sec' % GEN_BATCH_HEADLINE:
                round(gen4k8_tps, 1),
            'ppl_w4a8_samples_per_sec': round(ppl4_sps, 3),
            'ppl_w4a8_tops': round(ppl4_tops, 1),
            'cap_13b_w4a8': {
                'note': 'llama-13B geometry on ONE 16 GB chip — only '
                        'runnable via int4x2 packed weights (bf16/int8 '
                        'exceed HBM); EXPERIMENTAL accuracy tier '
                        '(group-RTN int4; QUANT_AGREEMENT_7B_W4A8.json)',
                'gen_b32_samples_per_sec': round(gen13_sps, 3),
                'gen_b32_tokens_per_sec': round(gen13_tps, 1),
                'gen_b32_kv8_kernels_samples_per_sec':
                    round(gen13k8_sps, 3),
                'gen_b32_kv8_kernels_tokens_per_sec':
                    round(gen13k8_tps, 1),
                'ppl_b8_samples_per_sec': round(ppl13_sps, 3),
            },
            'value_bf16': round(_blend(ppl_sps, gen_sps) / n_chips, 3),
            'value_int8_b32': round(_blend(ppl_sps, gen8_sps) / n_chips, 3),
            'params_b': round(_param_count(CFG_7B) / 1e9, 2),
            'n_chips': n_chips,
            'platform': jax.devices()[0].platform,
            'device_kind': kind,
            'peak_tflops': peak,
            'quant_agreement': agreement,
            'shared_prefix': shared_leg,
            'batch_planner': _bench_planner(),
            'warm_path': _bench_warm_path(),
            'result_cache': _bench_result_cache(),
            'flight_recorder': _bench_flight_recorder(),
            'roofline': _bench_roofline(),
            'devprof': _bench_devprof(),
            'obshub': _bench_obshub(),
            'a100_est': a100,
            'a100_est_b32': a100_b32,
            'small': {
                'config': 'llama-1024x8, ppl b32xs512 (BENCH_r01 '
                          'continuity)',
                'value': round(small_value, 3),
                'ppl_samples_per_sec': round(small_ppl, 3),
                'gen_samples_per_sec': round(small_gen, 3),
                'gen_tokens_per_sec': round(small_tps, 1),
            },
        },
    }
    print(json.dumps(record))


if __name__ == '__main__':
    if '--warm-path-child' in sys.argv:
        _warm_path_child(sys.argv[sys.argv.index('--warm-path-child') + 1])
        sys.exit(0)
    if '--warm-path' in sys.argv:
        # standalone warm-path leg (device-free; runs on CPU hosts)
        print(json.dumps({'metric': 'warm_path', 'v': 1,
                          'detail': _bench_warm_path()}))
        sys.exit(0)
    if '--result-cache-child' in sys.argv:
        i = sys.argv.index('--result-cache-child')
        _result_cache_child(sys.argv[i + 1], sys.argv[i + 2])
        sys.exit(0)
    if '--result-cache' in sys.argv:
        # standalone result-store leg (device-free; runs on CPU hosts)
        print(json.dumps({'metric': 'result_cache', 'v': 1,
                          'detail': _bench_result_cache()}))
        sys.exit(0)
    if '--flight-recorder' in sys.argv:
        # standalone observability leg (device-free; runs on CPU hosts)
        print(json.dumps({'metric': 'flight_recorder', 'v': 1,
                          'detail': _bench_flight_recorder()}))
        sys.exit(0)
    if '--serve' in sys.argv:
        # standalone serve-daemon leg (device-free; runs on CPU hosts)
        print(json.dumps({'metric': 'serve', 'v': 1,
                          'detail': _bench_serve()}))
        sys.exit(0)
    if '--slo' in sys.argv:
        # standalone SLO burn-rate alerting leg (device-free)
        print(json.dumps({'metric': 'slo', 'v': 1,
                          'detail': _bench_slo()}))
        sys.exit(0)
    if '--continuous-batching' in sys.argv:
        # standalone continuous-batching leg (tiny JaxLM; CPU-runnable)
        print(json.dumps({'metric': 'continuous_batching', 'v': 1,
                          'detail': _bench_continuous()}))
        sys.exit(0)
    if '--prefix-cache' in sys.argv:
        # standalone radix-prefix-cache + speculative-decoding leg
        # (tiny JaxLM; CPU-runnable)
        print(json.dumps({'metric': 'prefix_cache', 'v': 1,
                          'detail': _bench_prefix()}))
        sys.exit(0)
    if '--roofline' in sys.argv:
        # standalone roofline/MFU/MBU leg (tiny JaxLM; CPU-runnable)
        print(json.dumps({'metric': 'roofline', 'v': 1,
                          'detail': _bench_roofline()}))
        sys.exit(0)
    if '--devprof' in sys.argv:
        # standalone device-introspection leg: compile audit +
        # measured-vs-modeled drift + sampled step profiling (tiny
        # JaxLM; CPU-runnable)
        print(json.dumps({'metric': 'devprof', 'v': 1,
                          'detail': _bench_devprof()}))
        sys.exit(0)
    if '--obshub' in sys.argv:
        # standalone observability-hub leg: multi-source ingest, tail
        # sampling, rollup queries, retention compaction (device-free)
        print(json.dumps({'metric': 'obshub', 'v': 1,
                          'detail': _bench_obshub()}))
        sys.exit(0)
    if '--lint' in sys.argv:
        # standalone oct-lint coverage smoke (pure stdlib; device-free)
        print(json.dumps({'metric': 'lint', 'v': 1,
                          'detail': _bench_lint()}))
        sys.exit(0)
    if '--chaos' in sys.argv:
        # standalone chaos-harness leg: live fault injection against a
        # real daemon, degradation invariants asserted (device-free)
        print(json.dumps({'metric': 'chaos', 'v': 1,
                          'detail': _bench_chaos()}))
        sys.exit(0)
    if '--loadgen' in sys.argv:
        # standalone load-generator leg: recorded-trace replay at >=10x
        # native pacing + a Poisson step vs a live autoscaler-enabled
        # daemon, SSE streaming throughout (device-free)
        print(json.dumps({'metric': 'loadgen', 'v': 1,
                          'detail': _bench_loadgen()}))
        sys.exit(0)
    if '--outbound' in sys.argv:
        # standalone outbound-API-scheduler leg: sweep wall-clock vs
        # the serial arrival-order baseline under injected provider
        # latency + a 429 throttle mix (device-free; stub provider)
        print(json.dumps({'metric': 'outbound', 'v': 1,
                          'detail': _bench_outbound()}))
        sys.exit(0)
    main()
