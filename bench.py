"""Headline benchmark: Llama-7B-class eval throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Workload mirrors the reference's hot loops (SURVEY.md §3.2-3.3) at the
BASELINE north-star scale (Llama-7B geometry, random init, bf16):

- PPL scoring: one jitted forward + shifted CE per batch — the MMLU/PIQA
  ranking path.  Reported with achieved TFLOP/s and MFU, flash attention on
  and off (nn/flash.py Pallas kernel vs einsum attention).
- Greedy generation: jitted prefill + while-loop KV-cache decode — the
  GSM8K path.

``vs_baseline``: the reference publishes no perf numbers (BASELINE.md), so
the baseline is an analytic single-A100-80GB estimate of the same blended
workload under generous assumptions for the reference stack (50% MFU
compute, 70% of 2.04TB/s HBM during decode; details in `detail.a100_est`).
BASELINE.json's north star is >=3x single-A100 samples/sec on a v5e-16;
tasks are partitioned per chip (runners/local.py), so 16 chips scale this
per-chip number linearly.

A smaller llama-1024x8 config is also timed for round-over-round
continuity with BENCH_r01 (detail.small).
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from opencompass_tpu.nn import (TransformerConfig, forward, greedy_generate,
                                init_params, sequence_nll)

CFG_7B = TransformerConfig.llama(
    vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
    num_kv_heads=32, intermediate_size=11008, max_seq_len=2048)

CFG_SMALL = TransformerConfig.llama(
    vocab_size=32000, hidden_size=1024, num_layers=8, num_heads=16,
    num_kv_heads=16, intermediate_size=2816, max_seq_len=2048)

# peak dense bf16 TFLOP/s per chip, for MFU
_PEAK_TFLOPS = {'TPU v5 lite': 197.0, 'TPU v5': 459.0, 'TPU v4': 275.0,
                'TPU v6 lite': 918.0}

PPL_BATCH, PPL_SEQ, PPL_ITERS = 16, 512, 6
GEN_BATCH, GEN_PROMPT, GEN_NEW = 32, 128, 64


def _param_count(cfg):
    D, F, L, V = (cfg.hidden_size, cfg.intermediate_size, cfg.num_layers,
                  cfg.vocab_size)
    per_layer = D * (cfg.q_dim + 2 * cfg.kv_dim) + cfg.q_dim * D + 3 * D * F
    return L * per_layer + 2 * V * D


def _blend(a, b):
    """Harmonic blend of the two eval paths (equal sample weight)."""
    return 2.0 / (1.0 / a + 1.0 / b)


def _bench_ppl(params, cfg, iters, use_flash=True, batch=PPL_BATCH):
    @jax.jit
    def step(params, tokens, mask):
        logits = forward(params, cfg, tokens, mask, use_flash=use_flash)
        return sequence_nll(logits, tokens, mask)

    tokens = jnp.ones((batch, PPL_SEQ), jnp.int32)
    mask = jnp.ones((batch, PPL_SEQ), jnp.bool_)
    # host fetch (not block_until_ready) to fully drain compile + queue
    np.asarray(step(params, tokens, mask))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(params, tokens, mask)
    np.asarray(out)
    dt = (time.perf_counter() - t0) / iters
    samples_per_sec = batch / dt
    tflops = 2 * _param_count(cfg) * batch * PPL_SEQ / dt / 1e12
    return samples_per_sec, tflops


def _bench_gen(params, cfg, batch=GEN_BATCH):
    @jax.jit
    def step(params, tokens, mask):
        return greedy_generate(params, cfg, tokens, mask, GEN_NEW,
                               eos_token_id=None)[0]

    tokens = jnp.ones((batch, GEN_PROMPT), jnp.int32)
    mask = jnp.ones((batch, GEN_PROMPT), jnp.bool_)
    np.asarray(step(params, tokens, mask))  # compile + full sync
    t0 = time.perf_counter()
    out = step(params, tokens, mask)
    np.asarray(out)
    dt = time.perf_counter() - t0
    return batch / dt, batch * GEN_NEW / dt


def _a100_estimate(cfg):
    """Single-A100-80GB blended samples/sec under generous assumptions.

    The decode leg is modeled with the SAME weight-only int8 recipe the
    headline uses (1 byte/param re-read per step) so the vs_baseline
    ratio compares like with like; the bf16-decode figure is also
    reported for reference against value_bf16.
    """
    n = _param_count(cfg)
    peak, hbm = 312e12, 2.039e12
    ppl_sps = 0.5 * peak / (2 * n * PPL_SEQ)
    prefill = 2 * n * GEN_BATCH * GEN_PROMPT / (0.5 * peak)
    decode_bf16 = GEN_NEW * (2 * n) / (0.7 * hbm)
    decode_int8 = GEN_NEW * n / (0.7 * hbm)
    gen_sps_bf16 = GEN_BATCH / (prefill + decode_bf16)
    gen_sps = GEN_BATCH / (prefill + decode_int8)
    return {
        'blended': _blend(ppl_sps, gen_sps),
        'blended_bf16': _blend(ppl_sps, gen_sps_bf16),
        'ppl_samples_per_sec': round(ppl_sps, 2),
        'gen_samples_per_sec': round(gen_sps, 2),
        'gen_bf16_samples_per_sec': round(gen_sps_bf16, 2),
        'assumptions': 'A100-80GB SXM, 312 TFLOP/s bf16 at 50% MFU, '
                       'decode weight-bound at 70% of 2.04 TB/s HBM, '
                       'int8 weight-only decode (matching the headline)',
    }


def main():
    n_chips = max(1, len(jax.devices()))
    kind = getattr(jax.devices()[0], 'device_kind', '')
    peak = _PEAK_TFLOPS.get(kind)

    # continuity config first (small; freed before the 7B params land);
    # batch 32 matches BENCH_r01's 'PPL b32xs512' so values are comparable
    params = init_params(CFG_SMALL, jax.random.PRNGKey(0))
    small_ppl, _ = _bench_ppl(params, CFG_SMALL, 8, batch=32)
    small_gen, small_tps = _bench_gen(params, CFG_SMALL)
    small_value = _blend(small_ppl, small_gen) / n_chips
    del params

    params = jax.jit(init_params, static_argnums=0)(
        CFG_7B, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    ppl_sps, ppl_tflops = _bench_ppl(params, CFG_7B, PPL_ITERS)
    _, ppl_tflops_noflash = _bench_ppl(params, CFG_7B, PPL_ITERS,
                                       use_flash=False)
    gen_sps, gen_tps = _bench_gen(params, CFG_7B)
    del params
    jax.clear_caches()

    # int8 weight-only decode (nn/quant.py): the gen path is weight-read
    # bound, so halving weight bytes is the headline decode config.  One
    # fused init+quantize program keeps peak HBM at the bf16 model size.
    from opencompass_tpu.nn.quant import quantize_params
    qparams = jax.jit(
        lambda key: quantize_params(init_params(CFG_7B, key), CFG_7B))(
            jax.random.PRNGKey(0))
    jax.block_until_ready(qparams)
    jax.clear_caches()
    gen8_sps, gen8_tps = _bench_gen(qparams, CFG_7B)
    jax.clear_caches()
    # int8 KV cache on top (per-vector scales; decode-only) — reported in
    # detail, not the headline, as the more aggressive config
    import dataclasses
    cfg_kv = dataclasses.replace(CFG_7B, kv_quant=True)
    gen8kv_sps, gen8kv_tps = _bench_gen(qparams, cfg_kv)
    jax.clear_caches()
    # int8 halves both weight and cache bytes, freeing HBM for batch 64 —
    # the throughput configuration for batch-heavy gen suites
    gen8kv64_sps, gen8kv64_tps = _bench_gen(qparams, cfg_kv, batch=64)
    del qparams
    jax.clear_caches()

    # headline: bf16 scoring (exact measurement math) + int8 weight-only
    # generation (industry-standard inference quantization; per-channel
    # symmetric, activations/cache stay bf16)
    value = _blend(ppl_sps, gen8_sps) / n_chips
    a100 = _a100_estimate(CFG_7B)
    record = {
        'metric': 'eval samples/sec/chip (PPL b%dxs%d bf16 + gen b%d '
                  'p%d+%d int8-weights, llama-7B)' % (
                      PPL_BATCH, PPL_SEQ, GEN_BATCH, GEN_PROMPT, GEN_NEW),
        'value': round(value, 3),
        'unit': 'samples/sec/chip',
        'vs_baseline': round(value / a100['blended'], 3),
        'detail': {
            'ppl_samples_per_sec': round(ppl_sps, 3),
            'ppl_tflops': round(ppl_tflops, 1),
            'ppl_mfu': round(ppl_tflops / peak, 3) if peak else None,
            'ppl_tflops_noflash': round(ppl_tflops_noflash, 1),
            'flash_speedup': round(ppl_tflops / ppl_tflops_noflash, 3),
            'gen_samples_per_sec': round(gen8_sps, 3),
            'gen_tokens_per_sec': round(gen8_tps, 1),
            'gen_quantize': 'int8 weight-only (per-out-channel symmetric; '
                            'activations + KV cache bf16)',
            'gen_bf16_samples_per_sec': round(gen_sps, 3),
            'gen_bf16_tokens_per_sec': round(gen_tps, 1),
            'gen_int8kv_samples_per_sec': round(gen8kv_sps, 3),
            'gen_int8kv_tokens_per_sec': round(gen8kv_tps, 1),
            'gen_int8kv_b64_samples_per_sec': round(gen8kv64_sps, 3),
            'gen_int8kv_b64_tokens_per_sec': round(gen8kv64_tps, 1),
            'value_bf16': round(_blend(ppl_sps, gen_sps) / n_chips, 3),
            'params_b': round(_param_count(CFG_7B) / 1e9, 2),
            'n_chips': n_chips,
            'platform': jax.devices()[0].platform,
            'device_kind': kind,
            'peak_tflops': peak,
            'a100_est': a100,
            'small': {
                'config': 'llama-1024x8, ppl b32xs512 (BENCH_r01 '
                          'continuity)',
                'value': round(small_value, 3),
                'ppl_samples_per_sec': round(small_ppl, 3),
                'gen_samples_per_sec': round(small_gen, 3),
                'gen_tokens_per_sec': round(small_tps, 1),
            },
        },
    }
    print(json.dumps(record))


if __name__ == '__main__':
    main()
