"""Generate synthetic dataset files under ./data so eval configs run
offline (zero-egress environments, CI smoke tests, new-cluster bring-up).

    python tools/make_synth_data.py [--root ./data] [--rows 8]

Writes miniature but format-faithful files for the local-file dataset
families the flagship configs use (MMLU CSVs, GSM8K jsonl, MATH json,
C-Eval csv, ARC jsonl, SuperGLUE jsonl, triviaqa/nq tsv-ish, humaneval
jsonl, ...).  Content is synthetic; scores are meaningless — the point is
that the full pipeline (load → prompt → infer → eval → summarize) runs.
"""
from __future__ import annotations

import argparse
import csv
import json
import os
import os.path as osp
import sys

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
sys.path.insert(0, REPO)


def _w(path, text):
    os.makedirs(osp.dirname(path), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        f.write(text)


def _wjsonl(path, rows):
    _w(path, '\n'.join(json.dumps(r, ensure_ascii=False) for r in rows)
       + '\n')


def mmlu(root, n):
    # loader: opencompass_tpu/datasets/mmlu.py — {name}_{split}.csv rows
    # (question, A, B, C, D, target)
    from opencompass_tpu.config import Config
    cfg = Config.fromfile(osp.join(REPO,
                                   'configs/datasets/mmlu/mmlu_gen.py'))
    names = cfg['mmlu_all_sets']
    # realistic question lengths (real MMLU items average ~250 chars, so
    # a 5-shot prompt is 1.5-2k+ tokens): pad each question with a
    # deterministic filler clause so milestone runs exercise the same
    # truncation / long-prefill behavior as the real benchmark
    filler = ('Consider the following scenario drawn from %s, where a '
              'careful reading of the premises is required before any '
              'of the candidate answers can be ruled out, and partial '
              'credit is never awarded for an unjustified guess. ')
    for name in names:
        for split, k in (('dev', 5), ('test', n)):
            rows = []
            for i in range(k):
                gold = 'ABCD'[i % 4]
                body = filler % name.replace('_', ' ') * (1 + i % 2)
                rows.append([f'{body}Synthetic {name} question {i}?',
                             'alpha option %d' % i, 'beta option %d' % i,
                             'gamma option %d' % i, 'delta option %d' % i,
                             gold])
            out = osp.join(root, 'mmlu', split, f'{name}_{split}.csv')
            os.makedirs(osp.dirname(out), exist_ok=True)
            with open(out, 'w', newline='', encoding='utf-8') as f:
                csv.writer(f).writerows(rows)


def gsm8k(root, n):
    # loader: datasets/gsm8k.py — train/test jsonl {question, answer}
    for split in ('train', 'test'):
        rows = [{'question': f'What is {i} + {i + 1}?',
                 'answer': f'Adding gives {2 * i + 1}.\n#### {2 * i + 1}'}
                for i in range(n)]
        _wjsonl(osp.join(root, 'gsm8k', f'{split}.jsonl'), rows)


def math_ds(root, n):
    rows = {f'prob_{i}': {'problem': f'Compute ${i}+{i}$.',
                          'solution': f'${i}+{i}=\\boxed{{{2 * i}}}$',
                          'level': 'Level 1', 'type': 'Arithmetic'}
            for i in range(n)}
    _w(osp.join(root, 'math', 'math.json'),
       json.dumps(rows, ensure_ascii=False))


def ceval(root, n):
    from opencompass_tpu.config import Config
    cfg = Config.fromfile(osp.join(REPO,
                                   'configs/datasets/ceval/ceval_gen.py'))
    names = list(cfg['ceval_subject_mapping'])
    header = ['id', 'question', 'A', 'B', 'C', 'D', 'answer']
    for name in names:
        for split, k in (('dev', 5), ('val', n), ('test', n)):
            out = osp.join(root, 'ceval', 'formal_ceval', split,
                           f'{name}_{split}.csv')
            os.makedirs(osp.dirname(out), exist_ok=True)
            with open(out, 'w', newline='', encoding='utf-8') as f:
                w = csv.writer(f)
                hdr = list(header)
                if split == 'dev':
                    hdr = hdr + ['explanation']
                if split == 'test':
                    hdr = hdr[:-1]  # test ships without answers
                w.writerow(hdr)
                for i in range(k):
                    row = [i, f'合成{name}题目{i}？', '甲', '乙', '丙', '丁']
                    if split != 'test':
                        row.append('ABCD'[i % 4])
                    if split == 'dev':
                        row.append('解析略')
                    w.writerow(row)


def arc(root, n):
    for sub, fname in (('ARC-c', 'ARC-Challenge-Dev.jsonl'),
                       ('ARC-e', 'ARC-Easy-Dev.jsonl')):
        rows = []
        for i in range(n):
            rows.append({
                'question': {
                    'stem': f'Synthetic {sub} question {i}?',
                    'choices': [{'label': lab, 'text': f'opt {lab}{i}'}
                                for lab in 'ABCD'],
                },
                'answerKey': 'ABCD'[i % 4],
            })
        _wjsonl(osp.join(root, 'ARC', sub, fname), rows)


def superglue(root, n):
    # labels are the literal strings 'true'/'false' in SuperGLUE jsonl
    # (datasets/boolq.py, wsc.py, wic.py map them to letters)
    sg = osp.join(root, 'SuperGLUE')
    _wjsonl(osp.join(sg, 'BoolQ', 'val.jsonl'),
            [{'question': f'is {i} even', 'passage': f'number {i} facts',
              'label': 'true' if i % 2 == 0 else 'false'}
             for i in range(n)])
    _wjsonl(osp.join(sg, 'COPA', 'val.jsonl'),
            [{'premise': f'It rained on day {i}.', 'question': 'effect',
              'choice1': 'The ground got wet.', 'choice2': 'The sun rose.',
              'label': 0} for i in range(n)])
    _wjsonl(osp.join(sg, 'WSC', 'val.jsonl'),
            [{'text': f'The trophy did not fit in case {i} because it was '
                      'too big.',
              'target': {'span1_text': 'trophy', 'span1_index': 1,
                         'span2_text': 'it', 'span2_index': 9},
              'label': 'true'} for i in range(n)])
    _wjsonl(osp.join(sg, 'WiC', 'val.jsonl'),
            [{'word': 'bank', 'sentence1': f'river bank {i}',
              'sentence2': f'money bank {i}', 'label': 'false'}
             for i in range(n)])
    _wjsonl(osp.join(sg, 'CB', 'val.jsonl'),
            [{'premise': f'Premise {i}.', 'hypothesis': f'Hypothesis {i}.',
              'label': 'entailment'} for i in range(n)])
    _wjsonl(osp.join(sg, 'RTE', 'val.jsonl'),
            [{'premise': f'Premise {i}.', 'hypothesis': f'Hypothesis {i}.',
              'label': 'entailment'} for i in range(n)])
    # MultiRC nests passage -> questions -> answers
    _wjsonl(osp.join(sg, 'MultiRC', 'val.jsonl'),
            [{'passage': {
                'text': f'Paragraph {i}.',
                'questions': [{
                    'question': f'Question {i}?',
                    'answers': [{'text': f'Answer {i}', 'label': 1},
                                {'text': f'Wrong {i}', 'label': 0}],
                }]}} for i in range(n)])
    _wjsonl(osp.join(sg, 'AX-b', 'AX-b.jsonl'),
            [{'sentence1': f'S1 {i}.', 'sentence2': f'S2 {i}.',
              'label': 'entailment'} for i in range(n)])
    _wjsonl(osp.join(sg, 'AX-g', 'AX-g.jsonl'),
            [{'premise': f'P {i}.', 'hypothesis': f'H {i}.',
              'label': 'entailment'} for i in range(n)])


def qa(root, n):
    # loaders expect TSV with a python-literal answer list
    # (datasets/triviaqa.py, datasets/natural_question.py)
    def tsv(path):
        os.makedirs(osp.dirname(path), exist_ok=True)
        with open(path, 'w', newline='', encoding='utf-8') as f:
            w = csv.writer(f, delimiter='\t')
            for i in range(n):
                w.writerow([f'Who invented thing {i}?',
                            repr([f'Person {i}', f'Inventor {i}'])])
    for split in ('dev', 'test'):
        tsv(osp.join(root, 'triviaqa', f'trivia-{split}.qa.csv'))
        tsv(osp.join(root, 'nq', f'nq-{split}.qa.csv'))


def humaneval(root, n):
    rows = []
    for i in range(n):
        rows.append({
            'task_id': f'Synth/{i}',
            'prompt': f'def add{i}(a, b):\n    """Return a + b + {i}."""\n',
            'entry_point': f'add{i}',
            'canonical_solution': f'    return a + b + {i}\n',
            'test': (f'def check(candidate):\n'
                     f'    assert candidate(1, 2) == {3 + i}\n'),
        })
    _wjsonl(osp.join(root, 'humaneval', 'human-eval-v2.jsonl'), rows)


GENERATORS = {
    'mmlu': mmlu, 'gsm8k': gsm8k, 'math': math_ds, 'ceval': ceval,
    'arc': arc, 'superglue': superglue, 'qa': qa, 'humaneval': humaneval,
}


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--root', default='./data')
    parser.add_argument('--rows', type=int, default=8,
                        help='test rows per subset')
    parser.add_argument('--only', nargs='*', choices=sorted(GENERATORS),
                        help='subset of families (default: all)')
    args = parser.parse_args()
    for name in (args.only or sorted(GENERATORS)):
        GENERATORS[name](args.root, args.rows)
        print(f'wrote synthetic {name} under {args.root}')


if __name__ == '__main__':
    main()
