#!/usr/bin/env python
"""Render a run's obs trace report (standalone twin of
``python -m opencompass_tpu.cli trace``).

Usage::

    python tools/trace_report.py outputs/demo/20240101_120000
    python tools/trace_report.py outputs/demo            # latest run
    python tools/trace_report.py path/to/events.jsonl --json

See docs/observability.md for the event schema and how to read the
report.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from opencompass_tpu.obs.report import main  # noqa: E402

if __name__ == '__main__':
    raise SystemExit(main())
