"""Bad-case extraction: join predictions + results, write markdown reports
of the wrong cases per (model, dataset).

Parity: reference tools/case_analyzer.py:37-194 ('BadcaseShower').

    python tools/case_analyzer.py configs/eval_demo.py -w outputs/demo/<ts>
"""
import argparse
import json
import os
import os.path as osp
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from opencompass_tpu.config import Config  # noqa: E402
from opencompass_tpu.registry import TEXT_POSTPROCESSORS  # noqa: E402
from opencompass_tpu.utils.abbr import (dataset_abbr_from_cfg,  # noqa: E402
                                        model_abbr_from_cfg)
from opencompass_tpu.utils.build import build_dataset_from_cfg  # noqa: E402


def parse_args():
    parser = argparse.ArgumentParser(description='Extract bad cases')
    parser.add_argument('config', help='config file path')
    parser.add_argument('-w', '--work-dir', required=True,
                        help='the timestamped run directory')
    parser.add_argument('-o', '--out-dir', default=None,
                        help='report output dir (default {work_dir}/badcase)')
    return parser.parse_args()


def _norm(eval_cfg, value, key):
    if key in eval_cfg:
        cfg = dict(eval_cfg[key])
        proc = cfg.pop('type')
        if isinstance(proc, str):
            proc = TEXT_POSTPROCESSORS.get(proc)
        if proc:
            return proc(str(value), **cfg)
    return str(value)


def analyze(model_cfg, dataset_cfg, work_dir, out_dir):
    m_abbr = model_abbr_from_cfg(model_cfg)
    d_abbr = dataset_abbr_from_cfg(dataset_cfg)
    pred_path = osp.join(work_dir, 'predictions', m_abbr, f'{d_abbr}.json')
    if not osp.exists(pred_path):
        return None
    with open(pred_path) as f:
        preds = json.load(f)

    dataset = build_dataset_from_cfg(dataset_cfg)
    out_col = dataset_cfg['reader_cfg']['output_column']
    refs = dataset.test[out_col] if out_col else []
    eval_cfg = dataset_cfg.get('eval_cfg', {})

    lines = [f'# Bad cases: {m_abbr} / {d_abbr}', '']
    n_bad = 0
    for i in range(len(preds)):
        rec = preds[str(i)]
        pred = rec.get('prediction')
        if isinstance(pred, list):  # condprob vector
            continue
        gold = refs[i] if i < len(refs) else None
        if _norm(eval_cfg, pred, 'pred_postprocessor') == \
                _norm(eval_cfg, gold, 'dataset_postprocessor'):
            continue
        n_bad += 1
        prompt = rec.get('origin_prompt', '')
        if not prompt:
            # PPL-mode records keep per-label {'label: X': {prompt, PPL}}
            # entries instead of one origin_prompt — show each candidate
            # with its score so the ranking mistake is inspectable
            labels = {k[len('label: '):]: v for k, v in rec.items()
                      if k.startswith('label: ') and isinstance(v, dict)}
            if labels:
                prompt = '\n\n'.join(
                    f"[{lab}] PPL={v.get('PPL'):.4f}\n{v.get('prompt', '')}"
                    for lab, v in labels.items())
        lines += [f'## case {i}', '### prompt', '```',
                  str(prompt)[:2000], '```',
                  f'### prediction\n`{pred}`', f'### gold\n`{gold}`', '']
    os.makedirs(out_dir, exist_ok=True)
    report = osp.join(out_dir, f'{m_abbr}_{d_abbr}.md')
    with open(report, 'w') as f:
        f.write('\n'.join(lines))
    print(f'{m_abbr}/{d_abbr}: {n_bad} bad cases → {report}')
    return report


def main():
    args = parse_args()
    cfg = Config.fromfile(args.config)
    out_dir = args.out_dir or osp.join(args.work_dir, 'badcase')
    for model_cfg in cfg.get('models', []):
        for dataset_cfg in cfg.get('datasets', []):
            analyze(model_cfg, dataset_cfg, args.work_dir, out_dir)


if __name__ == '__main__':
    main()
