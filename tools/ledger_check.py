#!/usr/bin/env python
"""Gate CI on the cross-run performance regression ledger (standalone
twin of ``python -m opencompass_tpu.cli ledger check``).

Exits **2** when the latest run's tokens/s or accuracy regressed past
the thresholds vs the baseline (pinned, or the previous run), so a perf
regression in a PR fails loudly instead of landing silently.

Usage::

    python tools/ledger_check.py outputs/demo                # work root
    python tools/ledger_check.py --ledger /path/cache/ledger
    python tools/ledger_check.py --baseline 20260801_120000 ...
    python tools/ledger_check.py --trajectory BENCH_TRAJECTORY.json

See docs/observability.md ("Regression ledger") for the record schema
and baseline pinning.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from opencompass_tpu.ledger.cli import main  # noqa: E402

if __name__ == '__main__':
    raise SystemExit(main(['check'] + sys.argv[1:]))
