"""Measure quantized-vs-bf16 eval agreement at real model geometry.

CLI over :mod:`opencompass_tpu.nn.agreement` (metric design notes live
there).  The headline bench (bench.py) scores PPL with W8A8 and
generates with W8A8 + an int8 or int4 KV cache; tests/test_quant.py pins those recipes'
accuracy at toy and llama-512x4 scale; this tool pins them at full
geometry (default: llama-7B, 4096x32) on the real chip, where
quantization error has had 32 layers x 4096 channels to compound.

Memory: the two model variants never coexist — the bf16 phase runs
first, params are dropped and caches cleared, then one fused
init+quantize jit rebuilds the SAME weights (same PRNG key) as int8.
This keeps peak HBM at the bf16 model size (~13.5 GB at 7B on a 16 GB
v5e).

Usage:  python tools/quant_agreement.py [--geometry 7b] [--items 64]
Prints one JSON record; bench.py reports the same stats inline.
"""
import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from opencompass_tpu.nn import (TransformerConfig, greedy_generate,
                                init_params)
from opencompass_tpu.nn.agreement import (eval_pool, forced_decode,
                                          forced_stats, gen_stats,
                                          score_pool, scoring_stats)
from opencompass_tpu.nn.quant import quantize_params

GEOMETRIES = {
    '7b': dict(vocab_size=32000, hidden_size=4096, num_layers=32,
               num_heads=32, num_kv_heads=32, intermediate_size=11008,
               max_seq_len=2048),
    '1b': dict(vocab_size=32000, hidden_size=1024, num_layers=8,
               num_heads=16, num_kv_heads=16, intermediate_size=2816,
               max_seq_len=2048),
    '512x4': dict(vocab_size=2048, hidden_size=512, num_layers=4,
                  num_heads=8, num_kv_heads=8, intermediate_size=1408,
                  max_seq_len=128),
}


def _gen(params, cfg, prompts, pmask, n_new):
    step = jax.jit(lambda p, t, m: greedy_generate(
        p, cfg, t, m, n_new, eos_token_id=None)[0])
    return np.asarray(step(params, prompts, pmask))


def measure(geometry='7b', items=64, choices=4, seq=128, gen_batch=32,
            gen_prompt=128, gen_new=64, seed=0, quant='w8a8-kv8'):
    """``quant``: 'w8a8-kv8' (the serving recipe — int8 KV through the
    Pallas decode kernel), 'w8a8-kv4' (capacity cache), or
    'w4a8-kv8'/'w4a8-kv4' (packed int4x2 weights — nn/quant.py —
    group-RTN, coarser)."""
    weight_mode = 'int4x2' if quant.startswith('w4') else 'int8'
    kv_mode = 'int8' if quant.endswith('kv8') else 'int4'
    kv_tag = '8' if kv_mode == 'int8' else '4'
    cfg = TransformerConfig.llama(**GEOMETRIES[geometry])
    cfg_aq = dataclasses.replace(cfg, act_quant=True)
    cfg_hl = dataclasses.replace(cfg, act_quant=True, kv_quant=kv_mode)
    tokens, mask, prompts, pmask = eval_pool(cfg, items, choices, seq,
                                             gen_batch, gen_prompt)
    key = jax.random.PRNGKey(seed)

    def note(msg):
        print('[quant_agreement] %s (t=%.0fs)'
              % (msg, time.perf_counter() - t0), file=sys.stderr)

    t0 = time.perf_counter()
    params = jax.jit(init_params, static_argnums=0)(cfg, key)
    jax.block_until_ready(params)
    note('bf16 params ready')
    nll_fp = score_pool(params, cfg, tokens, mask)
    note('bf16 scoring done')
    out_fp = _gen(params, cfg, prompts, pmask, gen_new)
    note('bf16 greedy done')
    # forced decode re-walks a 16-row slice: at 7B the batch-32 cache plus
    # the scan's stacked outputs overshoots the 16 GB chip by kilobytes
    fr = min(prompts.shape[0], 16)
    forced = jnp.asarray(out_fp[:fr])
    lp_fp, am_fp, margin_fp, _ = forced_decode(params, cfg, prompts[:fr],
                                               pmask[:fr], forced)
    note('bf16 forced decode done')
    del params
    jax.clear_caches()

    # same key => same weights, re-materialized straight into int8 so the
    # bf16 and int8 trees never coexist in HBM
    qparams = jax.jit(
        lambda k: quantize_params(init_params(cfg, k), cfg,
                                  mode=weight_mode))(key)
    jax.block_until_ready(qparams)
    note('%s params ready' % weight_mode)
    wtag_note = quant.split('-')[0]
    nll_q = score_pool(qparams, cfg_aq, tokens, mask)
    note('%s scoring done' % wtag_note)
    out_q = _gen(qparams, cfg_hl, prompts, pmask, gen_new)
    note('%s greedy done' % quant)
    lp_q, am_q, _, rank_q = forced_decode(qparams, cfg_hl, prompts[:fr],
                                          pmask[:fr], forced)
    note('%s forced decode done' % quant)
    del qparams
    jax.clear_caches()

    wtag = quant.split('-')[0]
    return {
        'geometry': geometry,
        'quant': quant,
        'config': '%dx%d heads=%d vocab=%d' % (
            cfg.hidden_size, cfg.num_layers, cfg.num_heads, cfg.vocab_size),
        'platform': jax.devices()[0].platform,
        'scoring_%s_vs_bf16' % wtag: scoring_stats(nll_fp, nll_q, choices),
        'scoring_pool': {'items': items, 'choices': choices, 'seq': seq},
        'gen_%skv%s_vs_bf16' % (wtag, kv_tag): gen_stats(out_fp, out_q),
        'forced_decode_%skv%s_vs_bf16' % (wtag, kv_tag): forced_stats(
            forced, am_fp, margin_fp, lp_fp, am_q, rank_q, lp_q),
        'gen_pool': {'batch': gen_batch, 'prompt': gen_prompt,
                     'new': gen_new, 'forced_rows': fr},
        'wallclock_sec': round(time.perf_counter() - t0, 1),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('--geometry', default='7b', choices=sorted(GEOMETRIES))
    ap.add_argument('--items', type=int, default=64)
    ap.add_argument('--choices', type=int, default=4)
    ap.add_argument('--seq', type=int, default=128)
    ap.add_argument('--gen-batch', type=int, default=32)
    ap.add_argument('--gen-prompt', type=int, default=128)
    ap.add_argument('--gen-new', type=int, default=64)
    ap.add_argument('--quant', default='w8a8-kv8',
                    choices=['w8a8-kv8', 'w8a8-kv4', 'w4a8-kv8',
                             'w4a8-kv4'])
    args = ap.parse_args()
    rec = measure(args.geometry, args.items, args.choices, args.seq,
                  args.gen_batch, args.gen_prompt, args.gen_new,
                  quant=args.quant)
    print(json.dumps(rec))


if __name__ == '__main__':
    main()
