#!/usr/bin/env python
"""Standalone entry point for oct-lint, the project's invariant-
enforcing static analyzer (same body as ``python -m
opencompass_tpu.cli lint``; docs/static_analysis.md).

Usage::

    python tools/lint.py                    # report findings
    python tools/lint.py --check            # CI gate (exit 2 on
                                            # unbaselined findings)
    python tools/lint.py --json             # machine-readable report
    python tools/lint.py --list-rules
    python tools/lint.py --update-baseline --reason '...'

Rules OCT001..OCT007: durable-append discipline, atomic-replace state
files, ``# guarded-by:`` lock discipline, thread hygiene, injected-
clock discipline, host-sync-in-jit, and jit retrace risk.
"""
import os.path as osp
import sys

sys.path.insert(
    0, osp.dirname(osp.dirname(osp.abspath(__file__))))

from opencompass_tpu.analysis.linter import main  # noqa: E402

if __name__ == '__main__':
    raise SystemExit(main())
