"""Render the exact prompts a config would produce — without running
inference.

Parity: reference tools/prompt_viewer.py:16-217 (minus the curses menu; use
``-p pattern`` to filter datasets, ``-a`` for all, ``-n count`` for how many
prompts per dataset).

    python tools/prompt_viewer.py configs/eval_demo.py -a -n 2
"""
import argparse
import fnmatch
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from opencompass_tpu.config import Config  # noqa: E402
from opencompass_tpu.registry import (ICL_PROMPT_TEMPLATES,  # noqa: E402
                                      ICL_RETRIEVERS)
from opencompass_tpu.utils.abbr import (dataset_abbr_from_cfg,  # noqa: E402
                                        model_abbr_from_cfg)
from opencompass_tpu.utils.build import (build_dataset_from_cfg,  # noqa: E402
                                         build_model_from_cfg)


def parse_args():
    parser = argparse.ArgumentParser(
        description='View the prompts an eval config will produce')
    parser.add_argument('config', help='config file path')
    parser.add_argument('-a', '--all', action='store_true',
                        help='show all datasets (default: first)')
    parser.add_argument('-p', '--pattern', type=str,
                        help='fnmatch pattern over dataset abbrs')
    parser.add_argument('-n', '--count', type=int, default=1,
                        help='prompts to display per dataset')
    return parser.parse_args()


def render_prompts(model_cfg, dataset_cfg, count: int):
    infer_cfg = dataset_cfg['infer_cfg']
    dataset = build_dataset_from_cfg(dataset_cfg)
    model_cfg = dict(model_cfg)
    model_cfg['tokenizer_only'] = True
    try:
        model = build_model_from_cfg(model_cfg)
    except Exception:
        from opencompass_tpu.models import FakeModel
        model = FakeModel()

    ice_template = prompt_template = None
    if 'ice_template' in infer_cfg:
        ice_template = ICL_PROMPT_TEMPLATES.build(infer_cfg['ice_template'])
    if 'prompt_template' in infer_cfg:
        prompt_template = ICL_PROMPT_TEMPLATES.build(
            infer_cfg['prompt_template'])
    retriever_cfg = dict(infer_cfg['retriever'])
    retriever_cfg['dataset'] = dataset
    retriever = ICL_RETRIEVERS.build(retriever_cfg)

    fix_id_list = infer_cfg.get('inferencer', {}).get('fix_id_list')
    ice_idx_list = retriever.retrieve(fix_id_list) if fix_id_list \
        else retriever.retrieve()

    inferencer_type = str(infer_cfg.get('inferencer', {}).get('type', ''))
    mode = 'ppl' if 'PPL' in inferencer_type else 'gen'
    for idx in range(min(count, len(ice_idx_list))):
        ice = retriever.generate_ice(ice_idx_list[idx],
                                     ice_template=ice_template)
        if mode == 'ppl':
            labels = retriever.get_labels(ice_template=ice_template,
                                          prompt_template=prompt_template)
            for label in labels:
                prompt = retriever.generate_label_prompt(
                    idx, ice, label, ice_template=ice_template,
                    prompt_template=prompt_template)
                print(f'---------- [{idx}] label: {label} ----------')
                print(model.parse_template(prompt, mode='ppl'))
        else:
            prompt = retriever.generate_prompt_for_generate_task(
                idx, ice, ice_template=ice_template,
                prompt_template=prompt_template)
            print(f'---------- [{idx}] ----------')
            print(model.parse_template(prompt, mode='gen'))


def main():
    args = parse_args()
    cfg = Config.fromfile(args.config)
    datasets = cfg['datasets']
    model_cfgs = cfg.get('models') or [{}]
    if args.pattern:
        datasets = [d for d in datasets if fnmatch.fnmatch(
            dataset_abbr_from_cfg(d), args.pattern)]
        model_cfg = model_cfgs[0]
    elif not args.all and sys.stdin.isatty() \
            and (len(datasets) > 1 or len(model_cfgs) > 1):
        # interactive picker, one selection per list (reference
        # tools/prompt_viewer.py + utils/menu.py); degrades to a numbered
        # stdin prompt on dumb terminals
        from opencompass_tpu.utils import Menu
        model_names = [model_abbr_from_cfg(m) if m else '-'
                       for m in model_cfgs]
        ds_names = [dataset_abbr_from_cfg(d) for d in datasets]
        chosen = Menu([model_names, ds_names],
                      prompts=['Choose a model:', 'Choose a dataset:']).run()
        model_cfg = model_cfgs[model_names.index(chosen[0])]
        datasets = [datasets[ds_names.index(chosen[1])]]
    else:
        model_cfg = model_cfgs[0]
        if not args.all:
            datasets = datasets[:1]  # non-interactive default: first only
    if not datasets:
        raise SystemExit('no datasets match')
    for dataset_cfg in datasets:
        abbr = dataset_abbr_from_cfg(dataset_cfg)
        model_abbr = model_abbr_from_cfg(model_cfg) if model_cfg else '-'
        print(f'========== {model_abbr} / {abbr} ==========')
        render_prompts(model_cfg, dataset_cfg, args.count)


if __name__ == '__main__':
    main()
