"""Smoke-test an API model config: template parsing + a few short
generations.

Parity: reference tools/test_api_model.py:156-206.

    python tools/test_api_model.py configs/models/openai_gpt4.py [-n 2]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from opencompass_tpu.config import Config  # noqa: E402
from opencompass_tpu.utils.abbr import model_abbr_from_cfg  # noqa: E402
from opencompass_tpu.utils.build import build_model_from_cfg  # noqa: E402
from opencompass_tpu.utils.prompt import PromptList  # noqa: E402

PROBES = [
    'Hello! Reply with one word.',
    PromptList([
        dict(role='HUMAN', prompt='What is 2+2? Answer with a digit.'),
    ]),
]


def main():
    parser = argparse.ArgumentParser(description='API model smoke test')
    parser.add_argument('config', help='model config file')
    parser.add_argument('-n', type=int, default=2,
                        help='number of probe prompts')
    args = parser.parse_args()

    cfg = Config.fromfile(args.config)
    for model_cfg in cfg['models']:
        abbr = model_abbr_from_cfg(model_cfg)
        print(f'=== {abbr} ===')
        model = build_model_from_cfg(model_cfg)
        try:
            ppl = model.get_ppl(['The capital of France is Paris.'])
            print(f'get_ppl probe: {ppl}')
        except NotImplementedError:
            print('get_ppl: not supported by this endpoint (chat API)')
        except Exception as exc:  # dead endpoint: keep probing templates
            print(f'get_ppl probe failed: {exc}')
        for probe in PROBES[:args.n]:
            parsed = model.parse_template(probe, mode='gen')
            print(f'--- parsed prompt ---\n{parsed}')
            try:
                out = model.generate_from_template([probe], max_out_len=16)
                print(f'--- response ---\n{out[0]!r}')
            except Exception as exc:  # noqa: BLE001 — smoke tool
                print(f'--- request failed: {exc}')


if __name__ == '__main__':
    main()
