mmlu_datasets = [
    {
        'abbr': 'lukaemon_mmlu_college_biology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_chemistry',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_computer_science',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_electrical_engineering',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'electrical_engineering',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_astronomy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'astronomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_anatomy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'anatomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_abstract_algebra',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'abstract_algebra',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_machine_learning',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'machine_learning',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_clinical_knowledge',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'clinical_knowledge',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_global_facts',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'global_facts',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_management',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'management',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_nutrition',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'nutrition',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_marketing',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'marketing',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_accounting',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_accounting',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_geography',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_geography',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_international_law',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'international_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_scenarios',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_scenarios',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_computer_security',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'computer_security',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_microeconomics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_microeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_law',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_medical_genetics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'medical_genetics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_psychology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_jurisprudence',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'jurisprudence',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_world_religions',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'world_religions',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_philosophy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'philosophy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_virology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'virology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_chemistry',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_public_relations',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'public_relations',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_macroeconomics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_macroeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_sexuality',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_sexuality',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_elementary_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'elementary_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_computer_science',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_european_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_european_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_business_ethics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'business_ethics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_disputes',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_disputes',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_statistics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_statistics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_miscellaneous',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'miscellaneous',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_formal_logic',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'formal_logic',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_government_and_politics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_government_and_politics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_prehistory',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'prehistory',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_security_studies',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'security_studies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_biology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_logical_fallacies',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'logical_fallacies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_world_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_world_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_medicine',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_medicine',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_us_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_us_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_sociology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'sociology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_econometrics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'econometrics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_psychology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_aging',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_aging',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_us_foreign_policy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'us_foreign_policy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_conceptual_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'conceptual_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    }
]
ceval_datasets = [
    {
        'abbr': 'ceval-computer_network',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'computer_network',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于计算机网络考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-operating_system',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'operating_system',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于操作系统考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-computer_architecture',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'computer_architecture',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于计算机组成考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-college_programming',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'college_programming',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于大学编程考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-college_physics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'college_physics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于大学物理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-college_chemistry',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'college_chemistry',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于大学化学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-advanced_mathematics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'advanced_mathematics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高等数学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-probability_and_statistics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'probability_and_statistics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于概率统计考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-discrete_mathematics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'discrete_mathematics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于离散数学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-electrical_engineer',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'electrical_engineer',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册电气工程师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-metrology_engineer',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'metrology_engineer',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册计量师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_mathematics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中数学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_physics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_physics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中物理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_chemistry',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中化学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_biology',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_biology',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中生物考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_mathematics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中数学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_biology',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_biology',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中生物考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_physics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_physics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中物理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_chemistry',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中化学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-veterinary_medicine',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'veterinary_medicine',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于兽医学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-college_economics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'college_economics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于大学经济学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-business_administration',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'business_administration',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于工商管理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-marxism',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'marxism',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于马克思主义基本原理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-mao_zedong_thought',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'mao_zedong_thought',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于毛泽东思想和中国特色社会主义理论体系概论考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-education_science',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'education_science',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于教育学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-teacher_qualification',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'teacher_qualification',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于教师资格考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_politics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_politics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中政治考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_geography',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_geography',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中地理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_politics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_politics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中政治考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_geography',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_geography',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中地理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-modern_chinese_history',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'modern_chinese_history',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于近代史纲要考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-ideological_and_moral_cultivation',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'ideological_and_moral_cultivation',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于思想道德修养与法律基础考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-logic',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'logic',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于逻辑学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-law',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'law',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于法学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-chinese_language_and_literature',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'chinese_language_and_literature',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于中国语言文学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-art_studies',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'art_studies',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于艺术学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-professional_tour_guide',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'professional_tour_guide',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于导游资格考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-legal_professional',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'legal_professional',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于法律职业资格考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_chinese',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_chinese',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中语文考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_history',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_history',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中历史考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_history',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_history',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中历史考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-civil_servant',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'civil_servant',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于公务员考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-sports_science',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'sports_science',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于体育学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-plant_protection',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'plant_protection',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于植物保护考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-basic_medicine',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'basic_medicine',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于基础医学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-clinical_medicine',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'clinical_medicine',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于临床医学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-urban_and_rural_planner',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'urban_and_rural_planner',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册城乡规划师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-accountant',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'accountant',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册会计师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-fire_engineer',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'fire_engineer',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册消防工程师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-environmental_impact_assessment_engineer',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'environmental_impact_assessment_engineer',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于环境影响评价工程师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-tax_accountant',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'tax_accountant',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于税务师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-physician',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'physician',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于医师资格考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    }
]
arc_datasets = [
    {
        'abbr': 'ARC-c',
        'type': 'opencompass_tpu.datasets.arc.ARCDataset',
        'path': './data/ARC/ARC-c/ARC-Challenge-Dev.jsonl',
        'reader_cfg': {
            'input_columns': [
                'question',
                'textA',
                'textB',
                'textC',
                'textD'
            ],
            'output_column': 'answerKey'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'Question: {question}\nAnswer: {textA}',
                    'B': 'Question: {question}\nAnswer: {textB}',
                    'C': 'Question: {question}\nAnswer: {textC}',
                    'D': 'Question: {question}\nAnswer: {textD}'
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer'
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'ARC-e',
        'type': 'opencompass_tpu.datasets.arc.ARCDataset',
        'path': './data/ARC/ARC-e/ARC-Easy-Dev.jsonl',
        'reader_cfg': {
            'input_columns': [
                'question',
                'textA',
                'textB',
                'textC',
                'textD'
            ],
            'output_column': 'answerKey'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'Question: {question}\nAnswer: {textA}',
                    'B': 'Question: {question}\nAnswer: {textB}',
                    'C': 'Question: {question}\nAnswer: {textC}',
                    'D': 'Question: {question}\nAnswer: {textD}'
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer'
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    }
]
BoolQ_datasets = [
    {
        'abbr': 'BoolQ_letter',
        'type': 'BoolQDataset_V2',
        'path': './data/SuperGLUE/BoolQ/val.jsonl',
        'reader_cfg': {
            'input_columns': [
                'question',
                'passage'
            ],
            'output_column': 'label'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{passage}\nQuestion: {question}?\nA. Yes\nB. No\nAnswer: A',
                    'B': '{passage}\nQuestion: {question}?\nA. Yes\nB. No\nAnswer: B'
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer'
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    }
]
gsm8k_datasets = [
    {
        'abbr': 'gsm8k',
        'type': 'opencompass_tpu.datasets.gsm8k.GSM8KDataset',
        'path': './data/gsm8k',
        'reader_cfg': {
            'input_columns': [
                'question'
            ],
            'output_column': 'answer'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': "Question: A pencil costs 3 dollars and a notebook costs 5 dollars. How much do 2 pencils and 1 notebook cost?\nLet's think step by step\nAnswer:\nTwo pencils cost 2 x 3 = 6 dollars.\nAdding one notebook costs 6 + 5 = 11 dollars.\nThe answer is 11\n\nQuestion: A farm has 12 cows and sells a quarter of them. How many cows remain?\nLet's think step by step\nAnswer:\nA quarter of 12 is 12 / 4 = 3 cows sold.\nSo 12 - 3 = 9 cows remain.\nThe answer is 9\n\nQuestion: {question}\nLet's think step by step\nAnswer:{answer}"
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'max_out_len': 512
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'opencompass_tpu.datasets.gsm8k.gsm8k_postprocess'
            },
            'dataset_postprocessor': {
                'type': 'opencompass_tpu.datasets.gsm8k.gsm8k_dataset_postprocess'
            }
        }
    }
]
triviaqa_datasets = [
    {
        'abbr': 'triviaqa',
        'type': 'opencompass_tpu.datasets.triviaqa.TriviaQADataset',
        'path': './data/triviaqa',
        'reader_cfg': {
            'input_columns': [
                'question'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'ice_token': '</E>',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '</E>Answer these questions:\nQ: {question}\nA: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'max_out_len': 50
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.datasets.triviaqa.TriviaQAEvaluator'
            },
            'pred_role': 'BOT'
        }
    }
]
mmlu_summary_groups = [
    {
        'name': 'mmlu',
        'subsets': [
            'lukaemon_mmlu_college_biology',
            'lukaemon_mmlu_college_chemistry',
            'lukaemon_mmlu_college_computer_science',
            'lukaemon_mmlu_college_mathematics',
            'lukaemon_mmlu_college_physics',
            'lukaemon_mmlu_electrical_engineering',
            'lukaemon_mmlu_astronomy',
            'lukaemon_mmlu_anatomy',
            'lukaemon_mmlu_abstract_algebra',
            'lukaemon_mmlu_machine_learning',
            'lukaemon_mmlu_clinical_knowledge',
            'lukaemon_mmlu_global_facts',
            'lukaemon_mmlu_management',
            'lukaemon_mmlu_nutrition',
            'lukaemon_mmlu_marketing',
            'lukaemon_mmlu_professional_accounting',
            'lukaemon_mmlu_high_school_geography',
            'lukaemon_mmlu_international_law',
            'lukaemon_mmlu_moral_scenarios',
            'lukaemon_mmlu_computer_security',
            'lukaemon_mmlu_high_school_microeconomics',
            'lukaemon_mmlu_professional_law',
            'lukaemon_mmlu_medical_genetics',
            'lukaemon_mmlu_professional_psychology',
            'lukaemon_mmlu_jurisprudence',
            'lukaemon_mmlu_world_religions',
            'lukaemon_mmlu_philosophy',
            'lukaemon_mmlu_virology',
            'lukaemon_mmlu_high_school_chemistry',
            'lukaemon_mmlu_public_relations',
            'lukaemon_mmlu_high_school_macroeconomics',
            'lukaemon_mmlu_human_sexuality',
            'lukaemon_mmlu_elementary_mathematics',
            'lukaemon_mmlu_high_school_physics',
            'lukaemon_mmlu_high_school_computer_science',
            'lukaemon_mmlu_high_school_european_history',
            'lukaemon_mmlu_business_ethics',
            'lukaemon_mmlu_moral_disputes',
            'lukaemon_mmlu_high_school_statistics',
            'lukaemon_mmlu_miscellaneous',
            'lukaemon_mmlu_formal_logic',
            'lukaemon_mmlu_high_school_government_and_politics',
            'lukaemon_mmlu_prehistory',
            'lukaemon_mmlu_security_studies',
            'lukaemon_mmlu_high_school_biology',
            'lukaemon_mmlu_logical_fallacies',
            'lukaemon_mmlu_high_school_world_history',
            'lukaemon_mmlu_professional_medicine',
            'lukaemon_mmlu_high_school_mathematics',
            'lukaemon_mmlu_college_medicine',
            'lukaemon_mmlu_high_school_us_history',
            'lukaemon_mmlu_sociology',
            'lukaemon_mmlu_econometrics',
            'lukaemon_mmlu_high_school_psychology',
            'lukaemon_mmlu_human_aging',
            'lukaemon_mmlu_us_foreign_policy',
            'lukaemon_mmlu_conceptual_physics'
        ]
    }
]
ceval_summary_groups = [
    {
        'name': 'ceval-humanities',
        'subsets': [
            'ceval-modern_chinese_history',
            'ceval-ideological_and_moral_cultivation',
            'ceval-logic',
            'ceval-law',
            'ceval-chinese_language_and_literature',
            'ceval-art_studies',
            'ceval-professional_tour_guide',
            'ceval-legal_professional',
            'ceval-high_school_chinese',
            'ceval-high_school_history',
            'ceval-middle_school_history'
        ]
    },
    {
        'name': 'ceval-other',
        'subsets': [
            'ceval-civil_servant',
            'ceval-sports_science',
            'ceval-plant_protection',
            'ceval-basic_medicine',
            'ceval-clinical_medicine',
            'ceval-urban_and_rural_planner',
            'ceval-accountant',
            'ceval-fire_engineer',
            'ceval-environmental_impact_assessment_engineer',
            'ceval-tax_accountant',
            'ceval-physician'
        ]
    },
    {
        'name': 'ceval-stem',
        'subsets': [
            'ceval-computer_network',
            'ceval-operating_system',
            'ceval-computer_architecture',
            'ceval-college_programming',
            'ceval-college_physics',
            'ceval-college_chemistry',
            'ceval-advanced_mathematics',
            'ceval-probability_and_statistics',
            'ceval-discrete_mathematics',
            'ceval-electrical_engineer',
            'ceval-metrology_engineer',
            'ceval-high_school_mathematics',
            'ceval-high_school_physics',
            'ceval-high_school_chemistry',
            'ceval-high_school_biology',
            'ceval-middle_school_mathematics',
            'ceval-middle_school_biology',
            'ceval-middle_school_physics',
            'ceval-middle_school_chemistry',
            'ceval-veterinary_medicine'
        ]
    },
    {
        'name': 'ceval-social-science',
        'subsets': [
            'ceval-college_economics',
            'ceval-business_administration',
            'ceval-marxism',
            'ceval-mao_zedong_thought',
            'ceval-education_science',
            'ceval-teacher_qualification',
            'ceval-high_school_politics',
            'ceval-high_school_geography',
            'ceval-middle_school_politics',
            'ceval-middle_school_geography'
        ]
    },
    {
        'name': 'ceval',
        'subsets': [
            'ceval-computer_network',
            'ceval-operating_system',
            'ceval-computer_architecture',
            'ceval-college_programming',
            'ceval-college_physics',
            'ceval-college_chemistry',
            'ceval-advanced_mathematics',
            'ceval-probability_and_statistics',
            'ceval-discrete_mathematics',
            'ceval-electrical_engineer',
            'ceval-metrology_engineer',
            'ceval-high_school_mathematics',
            'ceval-high_school_physics',
            'ceval-high_school_chemistry',
            'ceval-high_school_biology',
            'ceval-middle_school_mathematics',
            'ceval-middle_school_biology',
            'ceval-middle_school_physics',
            'ceval-middle_school_chemistry',
            'ceval-veterinary_medicine',
            'ceval-college_economics',
            'ceval-business_administration',
            'ceval-marxism',
            'ceval-mao_zedong_thought',
            'ceval-education_science',
            'ceval-teacher_qualification',
            'ceval-high_school_politics',
            'ceval-high_school_geography',
            'ceval-middle_school_politics',
            'ceval-middle_school_geography',
            'ceval-modern_chinese_history',
            'ceval-ideological_and_moral_cultivation',
            'ceval-logic',
            'ceval-law',
            'ceval-chinese_language_and_literature',
            'ceval-art_studies',
            'ceval-professional_tour_guide',
            'ceval-legal_professional',
            'ceval-high_school_chinese',
            'ceval-high_school_history',
            'ceval-middle_school_history',
            'ceval-civil_servant',
            'ceval-sports_science',
            'ceval-plant_protection',
            'ceval-basic_medicine',
            'ceval-clinical_medicine',
            'ceval-urban_and_rural_planner',
            'ceval-accountant',
            'ceval-fire_engineer',
            'ceval-environmental_impact_assessment_engineer',
            'ceval-tax_accountant',
            'ceval-physician'
        ]
    }
]
datasets = [
    {
        'abbr': 'lukaemon_mmlu_college_biology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_chemistry',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_computer_science',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_electrical_engineering',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'electrical_engineering',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about electrical engineering.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_astronomy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'astronomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about astronomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_anatomy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'anatomy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about anatomy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_abstract_algebra',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'abstract_algebra',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about abstract algebra.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_machine_learning',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'machine_learning',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about machine learning.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_clinical_knowledge',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'clinical_knowledge',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about clinical knowledge.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_global_facts',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'global_facts',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about global facts.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_management',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'management',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about management.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_nutrition',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'nutrition',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about nutrition.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_marketing',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'marketing',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about marketing.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_accounting',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_accounting',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional accounting.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_geography',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_geography',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school geography.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_international_law',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'international_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about international law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_scenarios',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_scenarios',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about moral scenarios.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_computer_security',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'computer_security',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about computer security.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_microeconomics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_microeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school microeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_law',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_law',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional law.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_medical_genetics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'medical_genetics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about medical genetics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_psychology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_jurisprudence',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'jurisprudence',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about jurisprudence.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_world_religions',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'world_religions',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about world religions.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_philosophy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'philosophy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about philosophy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_virology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'virology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about virology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_chemistry',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school chemistry.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_public_relations',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'public_relations',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about public relations.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_macroeconomics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_macroeconomics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school macroeconomics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_sexuality',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_sexuality',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about human sexuality.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_elementary_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'elementary_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about elementary mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_computer_science',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_computer_science',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school computer science.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_european_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_european_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school european history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_business_ethics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'business_ethics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about business ethics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_moral_disputes',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'moral_disputes',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about moral disputes.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_statistics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_statistics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school statistics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_miscellaneous',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'miscellaneous',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about miscellaneous.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_formal_logic',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'formal_logic',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about formal logic.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_government_and_politics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_government_and_politics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school government and politics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_prehistory',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'prehistory',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about prehistory.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_security_studies',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'security_studies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about security studies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_biology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_biology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school biology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_logical_fallacies',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'logical_fallacies',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about logical fallacies.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_world_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_world_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school world history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_professional_medicine',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'professional_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about professional medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_mathematics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school mathematics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_college_medicine',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'college_medicine',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about college medicine.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_us_history',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_us_history',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school us history.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_sociology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'sociology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about sociology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_econometrics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'econometrics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about econometrics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_high_school_psychology',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'high_school_psychology',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about high school psychology.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_human_aging',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'human_aging',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about human aging.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_us_foreign_policy',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'us_foreign_policy',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about us foreign policy.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'lukaemon_mmlu_conceptual_physics',
        'type': 'opencompass_tpu.datasets.mmlu.MMLUDataset',
        'path': './data/mmlu/',
        'name': 'conceptual_physics',
        'reader_cfg': {
            'input_columns': [
                'input',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'target',
            'train_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A\n',
                    'B': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B\n',
                    'C': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C\n',
                    'D': '{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D\n'
                }
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: A',
                    'B': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: B',
                    'C': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: C',
                    'D': 'The following are multiple choice questions (with answers) about conceptual physics.\n</E>{input}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\nAnswer: D'
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'ceval-computer_network',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'computer_network',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于计算机网络考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-operating_system',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'operating_system',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于操作系统考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-computer_architecture',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'computer_architecture',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于计算机组成考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-college_programming',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'college_programming',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于大学编程考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-college_physics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'college_physics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于大学物理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-college_chemistry',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'college_chemistry',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于大学化学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-advanced_mathematics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'advanced_mathematics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高等数学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-probability_and_statistics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'probability_and_statistics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于概率统计考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-discrete_mathematics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'discrete_mathematics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于离散数学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-electrical_engineer',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'electrical_engineer',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册电气工程师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-metrology_engineer',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'metrology_engineer',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册计量师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_mathematics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中数学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_physics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_physics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中物理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_chemistry',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中化学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_biology',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_biology',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中生物考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_mathematics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_mathematics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中数学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_biology',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_biology',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中生物考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_physics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_physics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中物理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_chemistry',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_chemistry',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中化学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-veterinary_medicine',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'veterinary_medicine',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于兽医学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-college_economics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'college_economics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于大学经济学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-business_administration',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'business_administration',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于工商管理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-marxism',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'marxism',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于马克思主义基本原理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-mao_zedong_thought',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'mao_zedong_thought',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于毛泽东思想和中国特色社会主义理论体系概论考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-education_science',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'education_science',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于教育学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-teacher_qualification',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'teacher_qualification',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于教师资格考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_politics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_politics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中政治考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_geography',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_geography',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中地理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_politics',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_politics',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中政治考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_geography',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_geography',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中地理考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-modern_chinese_history',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'modern_chinese_history',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于近代史纲要考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-ideological_and_moral_cultivation',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'ideological_and_moral_cultivation',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于思想道德修养与法律基础考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-logic',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'logic',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于逻辑学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-law',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'law',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于法学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-chinese_language_and_literature',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'chinese_language_and_literature',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于中国语言文学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-art_studies',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'art_studies',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于艺术学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-professional_tour_guide',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'professional_tour_guide',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于导游资格考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-legal_professional',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'legal_professional',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于法律职业资格考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_chinese',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_chinese',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中语文考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-high_school_history',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'high_school_history',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于高中历史考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-middle_school_history',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'middle_school_history',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于初中历史考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-civil_servant',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'civil_servant',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于公务员考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-sports_science',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'sports_science',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于体育学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-plant_protection',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'plant_protection',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于植物保护考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-basic_medicine',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'basic_medicine',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于基础医学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-clinical_medicine',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'clinical_medicine',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于临床医学考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-urban_and_rural_planner',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'urban_and_rural_planner',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册城乡规划师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-accountant',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'accountant',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册会计师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-fire_engineer',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'fire_engineer',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于注册消防工程师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-environmental_impact_assessment_engineer',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'environmental_impact_assessment_engineer',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于环境影响评价工程师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-tax_accountant',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'tax_accountant',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于税务师考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ceval-physician',
        'type': 'opencompass_tpu.datasets.ceval.CEvalDataset',
        'path': './data/ceval/formal_ceval',
        'name': 'physician',
        'reader_cfg': {
            'input_columns': [
                'question',
                'A',
                'B',
                'C',
                'D'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'val'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'begin': '</E>',
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '以下是中国关于医师资格考试的单项选择题，请选出其中的正确答案。\n{question}\nA. {A}\nB. {B}\nC. {C}\nD. {D}\n答案: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                },
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'fix_id_list': [
                    0,
                    1,
                    2,
                    3,
                    4
                ]
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'first-capital'
            }
        }
    },
    {
        'abbr': 'ARC-c',
        'type': 'opencompass_tpu.datasets.arc.ARCDataset',
        'path': './data/ARC/ARC-c/ARC-Challenge-Dev.jsonl',
        'reader_cfg': {
            'input_columns': [
                'question',
                'textA',
                'textB',
                'textC',
                'textD'
            ],
            'output_column': 'answerKey'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'Question: {question}\nAnswer: {textA}',
                    'B': 'Question: {question}\nAnswer: {textB}',
                    'C': 'Question: {question}\nAnswer: {textC}',
                    'D': 'Question: {question}\nAnswer: {textD}'
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer'
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'ARC-e',
        'type': 'opencompass_tpu.datasets.arc.ARCDataset',
        'path': './data/ARC/ARC-e/ARC-Easy-Dev.jsonl',
        'reader_cfg': {
            'input_columns': [
                'question',
                'textA',
                'textB',
                'textC',
                'textD'
            ],
            'output_column': 'answerKey'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': 'Question: {question}\nAnswer: {textA}',
                    'B': 'Question: {question}\nAnswer: {textB}',
                    'C': 'Question: {question}\nAnswer: {textC}',
                    'D': 'Question: {question}\nAnswer: {textD}'
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer'
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'BoolQ_letter',
        'type': 'BoolQDataset_V2',
        'path': './data/SuperGLUE/BoolQ/val.jsonl',
        'reader_cfg': {
            'input_columns': [
                'question',
                'passage'
            ],
            'output_column': 'label'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'A': '{passage}\nQuestion: {question}?\nA. Yes\nB. No\nAnswer: A',
                    'B': '{passage}\nQuestion: {question}?\nA. Yes\nB. No\nAnswer: B'
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer'
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    },
    {
        'abbr': 'gsm8k',
        'type': 'opencompass_tpu.datasets.gsm8k.GSM8KDataset',
        'path': './data/gsm8k',
        'reader_cfg': {
            'input_columns': [
                'question'
            ],
            'output_column': 'answer'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': "Question: A pencil costs 3 dollars and a notebook costs 5 dollars. How much do 2 pencils and 1 notebook cost?\nLet's think step by step\nAnswer:\nTwo pencils cost 2 x 3 = 6 dollars.\nAdding one notebook costs 6 + 5 = 11 dollars.\nThe answer is 11\n\nQuestion: A farm has 12 cows and sells a quarter of them. How many cows remain?\nLet's think step by step\nAnswer:\nA quarter of 12 is 12 / 4 = 3 cows sold.\nSo 12 - 3 = 9 cows remain.\nThe answer is 9\n\nQuestion: {question}\nLet's think step by step\nAnswer:{answer}"
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'max_out_len': 512
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            },
            'pred_postprocessor': {
                'type': 'opencompass_tpu.datasets.gsm8k.gsm8k_postprocess'
            },
            'dataset_postprocessor': {
                'type': 'opencompass_tpu.datasets.gsm8k.gsm8k_dataset_postprocess'
            }
        }
    },
    {
        'abbr': 'triviaqa',
        'type': 'opencompass_tpu.datasets.triviaqa.TriviaQADataset',
        'path': './data/triviaqa',
        'reader_cfg': {
            'input_columns': [
                'question'
            ],
            'output_column': 'answer',
            'train_split': 'dev',
            'test_split': 'dev'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'ice_token': '</E>',
                'template': {
                    'round': [
                        {
                            'role': 'HUMAN',
                            'prompt': '</E>Answer these questions:\nQ: {question}\nA: '
                        },
                        {
                            'role': 'BOT',
                            'prompt': '{answer}'
                        }
                    ]
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'max_out_len': 50
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.datasets.triviaqa.TriviaQAEvaluator'
            },
            'pred_role': 'BOT'
        }
    }
]
models = [
    {
        'type': 'opencompass_tpu.models.jax_lm.JaxLM',
        'abbr': 'llama-1b-jax',
        'path': '',
        'config': {
            'preset': 'llama',
            'vocab_size': 32000,
            'hidden_size': 2048,
            'num_layers': 16,
            'num_heads': 16,
            'num_kv_heads': 16,
            'intermediate_size': 5632,
            'max_seq_len': 2048
        },
        'max_seq_len': 2048,
        'batch_size': 16,
        'max_out_len': 64,
        'dtype': 'bfloat16',
        'quantize': 'w8a8-kv4',
        'parallel': {
            'data': -1,
            'model': 1
        },
        'run_cfg': {
            'num_devices': 1
        }
    }
]
summarizer = {
    'summary_groups': [
        {
            'name': 'mmlu',
            'subsets': [
                'lukaemon_mmlu_college_biology',
                'lukaemon_mmlu_college_chemistry',
                'lukaemon_mmlu_college_computer_science',
                'lukaemon_mmlu_college_mathematics',
                'lukaemon_mmlu_college_physics',
                'lukaemon_mmlu_electrical_engineering',
                'lukaemon_mmlu_astronomy',
                'lukaemon_mmlu_anatomy',
                'lukaemon_mmlu_abstract_algebra',
                'lukaemon_mmlu_machine_learning',
                'lukaemon_mmlu_clinical_knowledge',
                'lukaemon_mmlu_global_facts',
                'lukaemon_mmlu_management',
                'lukaemon_mmlu_nutrition',
                'lukaemon_mmlu_marketing',
                'lukaemon_mmlu_professional_accounting',
                'lukaemon_mmlu_high_school_geography',
                'lukaemon_mmlu_international_law',
                'lukaemon_mmlu_moral_scenarios',
                'lukaemon_mmlu_computer_security',
                'lukaemon_mmlu_high_school_microeconomics',
                'lukaemon_mmlu_professional_law',
                'lukaemon_mmlu_medical_genetics',
                'lukaemon_mmlu_professional_psychology',
                'lukaemon_mmlu_jurisprudence',
                'lukaemon_mmlu_world_religions',
                'lukaemon_mmlu_philosophy',
                'lukaemon_mmlu_virology',
                'lukaemon_mmlu_high_school_chemistry',
                'lukaemon_mmlu_public_relations',
                'lukaemon_mmlu_high_school_macroeconomics',
                'lukaemon_mmlu_human_sexuality',
                'lukaemon_mmlu_elementary_mathematics',
                'lukaemon_mmlu_high_school_physics',
                'lukaemon_mmlu_high_school_computer_science',
                'lukaemon_mmlu_high_school_european_history',
                'lukaemon_mmlu_business_ethics',
                'lukaemon_mmlu_moral_disputes',
                'lukaemon_mmlu_high_school_statistics',
                'lukaemon_mmlu_miscellaneous',
                'lukaemon_mmlu_formal_logic',
                'lukaemon_mmlu_high_school_government_and_politics',
                'lukaemon_mmlu_prehistory',
                'lukaemon_mmlu_security_studies',
                'lukaemon_mmlu_high_school_biology',
                'lukaemon_mmlu_logical_fallacies',
                'lukaemon_mmlu_high_school_world_history',
                'lukaemon_mmlu_professional_medicine',
                'lukaemon_mmlu_high_school_mathematics',
                'lukaemon_mmlu_college_medicine',
                'lukaemon_mmlu_high_school_us_history',
                'lukaemon_mmlu_sociology',
                'lukaemon_mmlu_econometrics',
                'lukaemon_mmlu_high_school_psychology',
                'lukaemon_mmlu_human_aging',
                'lukaemon_mmlu_us_foreign_policy',
                'lukaemon_mmlu_conceptual_physics'
            ]
        },
        {
            'name': 'ceval-humanities',
            'subsets': [
                'ceval-modern_chinese_history',
                'ceval-ideological_and_moral_cultivation',
                'ceval-logic',
                'ceval-law',
                'ceval-chinese_language_and_literature',
                'ceval-art_studies',
                'ceval-professional_tour_guide',
                'ceval-legal_professional',
                'ceval-high_school_chinese',
                'ceval-high_school_history',
                'ceval-middle_school_history'
            ]
        },
        {
            'name': 'ceval-other',
            'subsets': [
                'ceval-civil_servant',
                'ceval-sports_science',
                'ceval-plant_protection',
                'ceval-basic_medicine',
                'ceval-clinical_medicine',
                'ceval-urban_and_rural_planner',
                'ceval-accountant',
                'ceval-fire_engineer',
                'ceval-environmental_impact_assessment_engineer',
                'ceval-tax_accountant',
                'ceval-physician'
            ]
        },
        {
            'name': 'ceval-stem',
            'subsets': [
                'ceval-computer_network',
                'ceval-operating_system',
                'ceval-computer_architecture',
                'ceval-college_programming',
                'ceval-college_physics',
                'ceval-college_chemistry',
                'ceval-advanced_mathematics',
                'ceval-probability_and_statistics',
                'ceval-discrete_mathematics',
                'ceval-electrical_engineer',
                'ceval-metrology_engineer',
                'ceval-high_school_mathematics',
                'ceval-high_school_physics',
                'ceval-high_school_chemistry',
                'ceval-high_school_biology',
                'ceval-middle_school_mathematics',
                'ceval-middle_school_biology',
                'ceval-middle_school_physics',
                'ceval-middle_school_chemistry',
                'ceval-veterinary_medicine'
            ]
        },
        {
            'name': 'ceval-social-science',
            'subsets': [
                'ceval-college_economics',
                'ceval-business_administration',
                'ceval-marxism',
                'ceval-mao_zedong_thought',
                'ceval-education_science',
                'ceval-teacher_qualification',
                'ceval-high_school_politics',
                'ceval-high_school_geography',
                'ceval-middle_school_politics',
                'ceval-middle_school_geography'
            ]
        },
        {
            'name': 'ceval',
            'subsets': [
                'ceval-computer_network',
                'ceval-operating_system',
                'ceval-computer_architecture',
                'ceval-college_programming',
                'ceval-college_physics',
                'ceval-college_chemistry',
                'ceval-advanced_mathematics',
                'ceval-probability_and_statistics',
                'ceval-discrete_mathematics',
                'ceval-electrical_engineer',
                'ceval-metrology_engineer',
                'ceval-high_school_mathematics',
                'ceval-high_school_physics',
                'ceval-high_school_chemistry',
                'ceval-high_school_biology',
                'ceval-middle_school_mathematics',
                'ceval-middle_school_biology',
                'ceval-middle_school_physics',
                'ceval-middle_school_chemistry',
                'ceval-veterinary_medicine',
                'ceval-college_economics',
                'ceval-business_administration',
                'ceval-marxism',
                'ceval-mao_zedong_thought',
                'ceval-education_science',
                'ceval-teacher_qualification',
                'ceval-high_school_politics',
                'ceval-high_school_geography',
                'ceval-middle_school_politics',
                'ceval-middle_school_geography',
                'ceval-modern_chinese_history',
                'ceval-ideological_and_moral_cultivation',
                'ceval-logic',
                'ceval-law',
                'ceval-chinese_language_and_literature',
                'ceval-art_studies',
                'ceval-professional_tour_guide',
                'ceval-legal_professional',
                'ceval-high_school_chinese',
                'ceval-high_school_history',
                'ceval-middle_school_history',
                'ceval-civil_servant',
                'ceval-sports_science',
                'ceval-plant_protection',
                'ceval-basic_medicine',
                'ceval-clinical_medicine',
                'ceval-urban_and_rural_planner',
                'ceval-accountant',
                'ceval-fire_engineer',
                'ceval-environmental_impact_assessment_engineer',
                'ceval-tax_accountant',
                'ceval-physician'
            ]
        }
    ]
}
infer = {
    'partitioner': {
        'type': 'SizePartitioner',
        'max_task_size': 100000,
        'gen_task_coef': 20
    }
}
task_timeout = 14400
stall_timeout = 1800
work_dir = './outputs/suite_1b/20260731_010416'
