demo_gen_datasets = [
    {
        'type': 'opencompass_tpu.datasets.demo.DemoDataset',
        'abbr': 'demo-gen',
        'reader_cfg': {
            'input_columns': [
                'question'
            ],
            'output_column': 'answer'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': 'Q: {question}\nA: {answer}\n'
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': '</E>Q: {question}\nA:',
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever',
                'fix_id_list': [
                    0,
                    1,
                    2
                ]
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'max_out_len': 8
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.em.EMEvaluator'
            }
        }
    }
]
demo_ppl_datasets = [
    {
        'type': 'opencompass_tpu.datasets.demo.DemoDataset',
        'abbr': 'demo-ppl',
        'reader_cfg': {
            'input_columns': [
                'question'
            ],
            'output_column': 'parity',
            'test_range': '[0:8]'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'even': 'Q: is {question} even or odd?\nA: even',
                    'odd': 'Q: is {question} even or odd?\nA: odd'
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer'
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    }
]
datasets = [
    {
        'type': 'opencompass_tpu.datasets.demo.DemoDataset',
        'abbr': 'demo-gen',
        'reader_cfg': {
            'input_columns': [
                'question'
            ],
            'output_column': 'answer'
        },
        'infer_cfg': {
            'ice_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': 'Q: {question}\nA: {answer}\n'
            },
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': '</E>Q: {question}\nA:',
                'ice_token': '</E>'
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.fix_k.FixKRetriever',
                'fix_id_list': [
                    0,
                    1,
                    2
                ]
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.gen.GenInferencer',
                'max_out_len': 8
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.em.EMEvaluator'
            }
        }
    },
    {
        'type': 'opencompass_tpu.datasets.demo.DemoDataset',
        'abbr': 'demo-ppl',
        'reader_cfg': {
            'input_columns': [
                'question'
            ],
            'output_column': 'parity',
            'test_range': '[0:8]'
        },
        'infer_cfg': {
            'prompt_template': {
                'type': 'opencompass_tpu.icl.prompt_template.PromptTemplate',
                'template': {
                    'even': 'Q: is {question} even or odd?\nA: even',
                    'odd': 'Q: is {question} even or odd?\nA: odd'
                }
            },
            'retriever': {
                'type': 'opencompass_tpu.icl.retrievers.zero.ZeroRetriever'
            },
            'inferencer': {
                'type': 'opencompass_tpu.icl.inferencers.ppl.PPLInferencer'
            }
        },
        'eval_cfg': {
            'evaluator': {
                'type': 'opencompass_tpu.icl.evaluators.metrics.AccEvaluator'
            }
        }
    }
]
models = [
    {
        'type': 'opencompass_tpu.models.fake.FakeModel',
        'abbr': 'fake-demo',
        'path': 'fake',
        'max_seq_len': 2048,
        'batch_size': 4,
        'canned_responses': {
            'A:': '101'
        },
        'run_cfg': {
            'num_devices': 0
        }
    }
]
work_dir = './outputs/demo/20260730_185610'
